"""Shared foundations: errors, registries, type helpers.

TPU-native re-design of the roles played in the reference by dmlc-core
(``dmlc::Registry``, ``dmlc::Parameter``, logging/CHECK macros — see
reference ``3rdparty/dmlc-core`` and SURVEY.md §2.2) and by
``python/mxnet/base.py`` (error type, registry plumbing).  There is no C ABI
boundary here: the frontend talks straight to the JAX runtime, so the
242-entry ``c_api.h`` surface collapses into ordinary Python calls.
"""
from __future__ import annotations

import threading

import numpy as onp

__all__ = [
    "MXNetError",
    "Registry",
    "string_types",
    "numeric_types",
    "integer_types",
    "classproperty",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


string_types = (str,)
integer_types = (int, onp.integer)
numeric_types = (float, int, onp.generic)


class Registry:
    """A tiny name->object registry with alias support.

    Plays the role of ``dmlc::Registry`` / ``DMLC_REGISTRY_REGISTER`` in the
    reference (e.g. optimizer registry python/mxnet/optimizer/optimizer.py:44,
    initializer registry python/mxnet/initializer.py:41, metric registry
    python/mxnet/metric.py).
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._map: dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, obj=None, name: str | None = None, aliases=()):
        def _do(o):
            key = (name or getattr(o, "__name__", None) or str(o)).lower()
            with self._lock:
                self._map[key] = o
                for a in aliases:
                    self._map[a.lower()] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def alias(self, name: str):
        """Decorator registering an additional alias for a class."""

        def _do(o):
            with self._lock:
                self._map[name.lower()] = o
            return o

        return _do

    def get(self, name: str):
        try:
            return self._map[name.lower()]
        except KeyError:
            raise MXNetError(
                f"Cannot find {self.kind} '{name}'. "
                f"Registered: {sorted(self._map)}"
            ) from None

    def find(self, name: str):
        return self._map.get(name.lower())

    def create(self, name, *args, **kwargs):
        """Create an instance; `name` may already be an instance."""
        if not isinstance(name, str):
            return name
        return self.get(name)(*args, **kwargs)

    def list(self):
        return sorted(self._map)

    def __contains__(self, name):
        return name.lower() in self._map


class classproperty:
    def __init__(self, fget):
        self.fget = fget

    def __get__(self, obj, owner):
        return self.fget(owner)
