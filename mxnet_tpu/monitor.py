"""Monitor — per-op output statistics during training (reference
python/mxnet/monitor.py:33-160).

The reference installs a C-side executor monitor callback that fires on
every op output.  TPU-native: inside one jitted program individual op
outputs don't exist post-fusion, so the Monitor observes at the API
boundaries that do exist eagerly:

  * ``install(executor)`` — wraps ``Executor.forward`` and records every
    symbol output (and, with ``monitor_all``, the argument arrays).
  * ``install(block)`` — registers Gluon forward hooks on every child
    block, recording each block's outputs by name.

The tic/toc/toc_print protocol is unchanged.
"""
from __future__ import annotations

import re

import numpy as onp

from .base import MXNetError

__all__ = ["Monitor"]


class Monitor:
    """Collect activation statistics every `interval` batches.

    Parameters match reference monitor.py:52: ``interval`` (batches
    between samples), ``stat_func`` (NDArray -> NDArray/scalar, default
    mean(|x|)), ``pattern`` (regex filtering entry names), ``sort``
    (sort stats by name at toc), ``monitor_all`` (also record inputs/
    arguments, not only outputs).
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def asum_stat(x):
                """returns |x|/size(x), async execution."""
                arr = onp.asarray(getattr(x, "_data", x))
                return onp.abs(arr).mean()

            stat_func = asum_stat
        elif stat_func == "numerics":
            # Monitor 2.0 bridge: the telemetry.numerics summary
            # (l2/min/max/nan/inf/zero_frac) through the classic
            # tic/toc protocol — the same six numbers the in-graph
            # monitor records as tensor_stats
            from .telemetry import numerics as _nm

            def numerics_stat(x):
                row = _nm.stats_row(_nm.summary(
                    onp.asarray(getattr(x, "_data", x))))
                return [f"{k}={row[k]:.6g}" for k in _nm.STAT_FIELDS]

            stat_func = numerics_stat
        self.stat_func = stat_func
        self.interval = int(interval)
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all
        self._handles = []

    # ----------------------------------------------------------- hooks
    def _stat_helper(self, name, array):
        if not self.activated or not self.re_prog.match(name):
            return
        import jax

        data = getattr(array, "_data", array)
        if isinstance(data, jax.core.Tracer):
            # hook fired inside a jit trace (hybridized block): the
            # value is symbolic — per-child stats don't exist inside one
            # fused XLA program.  Only the eager (top-level) outputs are
            # observable; skip silently like the reference skips ops
            # fused out of existence.
            return
        self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        """Attach to an Executor, a Gluon Block, or a Module.

        A Module delegates to its ``install_monitor``: the monitor
        wraps the bound executor (group) immediately when bound, or at
        ``bind`` time otherwise — the legacy ``fit(monitor=...)``
        path from the reference, driveable from either end."""
        from .gluon.block import Block
        from .module.base_module import BaseModule
        from .symbol.executor import Executor

        if any(e is exe for e in self.exes):
            return  # idempotent: don't stack hooks/wrappers
        if isinstance(exe, BaseModule):
            self.exes.append(exe)
            exe.install_monitor(self)  # wraps exe's executor via this
            #                            install (Executor branch)
            return
        if isinstance(exe, Block):
            self._install_block(exe)
        elif isinstance(exe, Executor):
            self._install_executor(exe)
        else:
            raise MXNetError(
                f"Monitor.install expects an Executor, Block or "
                f"Module, got {type(exe)}")
        self.exes.append(exe)

    def _install_block(self, block):
        def make_hook(blk):
            def hook(b, inputs, outputs):
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                for i, o in enumerate(outs):
                    self._stat_helper(f"{blk.name}_output{i}", o)
                if self.monitor_all:
                    ins = inputs if isinstance(inputs, (list, tuple)) \
                        else [inputs]
                    for i, a in enumerate(ins):
                        self._stat_helper(f"{blk.name}_input{i}", a)
            return hook

        def walk(b):
            yield b
            for c in b._children.values():
                yield from walk(c)

        for child in walk(block):
            self._handles.append(
                child.register_forward_hook(make_hook(child)))

    def _install_executor(self, exe):
        monitor = self
        orig_forward = exe.forward

        def forward(is_train=False, **kwargs):
            out = orig_forward(is_train=is_train, **kwargs)
            for name, arr in exe.output_dict.items():
                monitor._stat_helper(name, arr)
            if monitor.monitor_all:
                for name, arr in zip(exe._symbol.list_arguments(),
                                     exe.arg_arrays):
                    monitor._stat_helper(name, arr)
            return out

        exe.forward = forward

    # -------------------------------------------------------- protocol
    def tic(self):
        """Start collecting for this batch if step % interval == 0
        (reference monitor.py:88)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """End collection; return list of (step, name, stat_str)
        (reference monitor.py:102)."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if not isinstance(v_list, (list, tuple)):
                v_list = [v_list]
            s = " ".join(str(v) for v in v_list)
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """End collection and print results (reference
        monitor.py:142)."""
        res = self.toc()
        for n, k, v in res:
            print(f"Batch: {n:7d} {k:30s} {v}")
        return res
