"""``mx.nd.contrib`` namespace — contrib ops exposed eagerly.

Reference parity: python/mxnet/ndarray/contrib.py over src/operator/contrib/
(SURVEY.md §2.3).  Ops land here as they are implemented in
mxnet_tpu/ops/contrib_ops.py; detection/transformer families are added in
later milestones.
"""
from __future__ import annotations

import sys

from ..ops.registry import _OPS, get_op
from . import _make_op_func

_this = sys.modules[__name__]


def _expose_contrib():
    for name in list(_OPS):
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            if short.isidentifier() and not hasattr(_this, short):
                setattr(_this, short, _make_op_func(get_op(name), short))


_expose_contrib()

# higher-order control flow (reference python/mxnet/ndarray/contrib.py
# foreach/while_loop/cond over src/operator/control_flow.cc)
from ..ops.control_flow_ops import cond, foreach, while_loop  # noqa: E402,F401
