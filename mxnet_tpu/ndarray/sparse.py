"""Sparse NDArray API surface: CSRNDArray / RowSparseNDArray.

Reference parity: python/mxnet/ndarray/sparse.py over the row_sparse/csr
storage types (include/mxnet/ndarray.h:61-65), cast_storage
(src/operator/tensor/cast_storage.cc), sparse dot
(src/operator/tensor/dot-inl.h DotCsrDnsDns/DotCsrTDnsRsp) and the lazy
sparse optimizer updates (src/operator/optimizer_op.cc SGD/AdaGrad
row_sparse kernels).

TPU-native design (SURVEY.md §7 "hard parts"): XLA/TPU has no sparse
buffer type, so sparse arrays stay *dense-backed with sparse metadata*
for general API use — but the EXECUTION tier below runs real sparse
compute on static-shape compressed forms:

  * CSR x dense matmuls run on a padded per-row COO view
    (``_csr_padded`` — [B, K] column ids + values, K = max row nnz),
    i.e. gather + contraction, touching O(nnz) weight rows instead of
    the dense [B, F] product;
  * the transposed product dot(csr.T, dense) scatter-adds into the
    touched feature rows only, returning a row_sparse gradient;
  * lazy optimizer updates (``sgd_update``/``adagrad_update`` here)
    gather the touched rows, apply the rule, and scatter back — rows
    the gradient does not touch keep bit-identical weight AND state
    (the reference's lazy_update contract).

Together these make embedding/FM-style sparse training cost O(nnz)
compute + memory traffic on the accelerator, not O(rows).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from .ndarray import NDArray, array, invoke, zeros as _dense_zeros


class BaseSparseNDArray(NDArray):
    """Dense-backed sparse array with CACHED metadata: ``indices``/
    ``indptr``/``data`` each need a host sync to compute (VERDICT r02
    weak #5 — a silent performance cliff when accessed in a loop), so
    results are memoized against the identity of the immutable backing
    jax buffer and recomputed only after an in-place update swaps it.
    """

    __slots__ = ("_meta_cache",)

    def _adopt(self, data):
        # every in-place mutation path funnels through _adopt: drop the
        # metadata cache (no stale reads, and no pinning of the
        # pre-mutation dense buffer in memory)
        self._meta_cache = None
        super()._adopt(data)

    def _cached_raw(self, name, compute):
        """Memoize ``compute()`` against the backing buffer (cleared by
        _adopt); single cache protocol for all metadata views."""
        store = getattr(self, "_meta_cache", None)
        if store is None:
            store = {}
            self._meta_cache = store
        if name not in store:
            store[name] = compute()
        return store[name]

    def _cached_meta(self, name, compute):
        # fresh wrapper over the (immutable) cached jax buffer: zero
        # recompute/copy cost, and caller-side __setitem__ adopts a new
        # buffer in the wrapper without touching the cache
        cached = self._cached_raw(name, compute)
        return type(cached)(cached._data)


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ()

    def __init__(self, data):
        super().__init__(data, stype="csr")

    @property
    def indices(self):
        def compute():
            a = onp.asarray(self._data)
            # row-major nonzero == concatenated per-row column indices
            _, cols = onp.nonzero(a)
            return array(cols, dtype="int64")
        return self._cached_meta("indices", compute)

    @property
    def indptr(self):
        def compute():
            a = onp.asarray(self._data)
            counts = onp.count_nonzero(a, axis=1)
            return array(onp.concatenate([[0], onp.cumsum(counts)]),
                         dtype="int64")
        return self._cached_meta("indptr", compute)

    @property
    def data(self):
        def compute():
            a = onp.asarray(self._data)
            return array(a[a != 0])
        return self._cached_meta("data", compute)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        if stype == "csr":
            return self
        raise MXNetError(f"cast_storage csr->{stype} unsupported")

    def _padded(self):
        """Static-shape compressed view: (cols [B, K] int32, vals
        [B, K]) with K = max row nnz, zero-padded — the TPU-native CSR
        form (gathers/scatters with static shapes; padding lanes carry
        value 0 so they contribute nothing to contractions)."""
        def compute():
            a = onp.asarray(self._data)
            counts = onp.count_nonzero(a, axis=1)
            k = max(int(counts.max()) if counts.size else 0, 1)
            rows, cols = onp.nonzero(a)
            pc = onp.zeros((a.shape[0], k), onp.int32)
            pv = onp.zeros((a.shape[0], k), a.dtype)
            pos = onp.concatenate([[0], onp.cumsum(counts)])
            within = onp.arange(len(rows)) - pos[rows]
            pc[rows, within] = cols
            pv[rows, within] = a[rows, cols]
            return array(pc, dtype="int32"), array(pv)
        pc, pv = self._cached_raw("padded", compute)
        return pc._data, pv._data


class RowSparseNDArray(BaseSparseNDArray):
    __slots__ = ()

    def __init__(self, data):
        super().__init__(data, stype="row_sparse")

    @property
    def indices(self):
        def compute():
            a = onp.asarray(self._data)
            nz = onp.nonzero(a.reshape(a.shape[0], -1).any(axis=1))[0]
            return array(nz, dtype="int64")
        return self._cached_meta("indices", compute)

    @property
    def data(self):
        def compute():
            a = onp.asarray(self._data)
            nz = a.reshape(a.shape[0], -1).any(axis=1)
            return array(a[nz])
        return self._cached_meta("data", compute)

    def retain(self, indices):
        idx = onp.asarray(indices._data
                          if isinstance(indices, NDArray) else indices,
                          dtype=onp.int64)
        mask = onp.zeros(self.shape[0], dtype=bool)
        mask[idx] = True
        d = jnp.where(jnp.asarray(mask).reshape((-1,) + (1,) *
                                                (self.ndim - 1)),
                      self._data, 0)
        return RowSparseNDArray(d)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        if stype == "row_sparse":
            return self
        raise MXNetError(f"cast_storage row_sparse->{stype} unsupported")

    def _compact(self):
        """(rows [R] int32, vals [R, ...]) — the nonzero rows and their
        values; the O(nnz) form the kvstore wire and the lazy optimizer
        updates run on."""
        def compute():
            a = onp.asarray(self._data)
            nz = onp.nonzero(a.reshape(a.shape[0], -1).any(axis=1))[0]
            # all-zero grad: R=0 — every downstream op (take, scatter,
            # wire frame) is a well-defined no-op, and the lazy-update
            # contract (untouched rows bit-identical, even under wd)
            # holds for EVERY row
            return (array(nz.astype(onp.int32), dtype="int32"),
                    array(a[nz]))
        rows, vals = self._cached_raw("compact", compute)
        return rows._data, vals._data


def cast_storage(arr, stype):
    if stype == "default":
        return NDArray(arr._data)
    if stype == "csr":
        return CSRNDArray(arr._data)
    if stype == "row_sparse":
        return RowSparseNDArray(arr._data)
    raise MXNetError(f"unknown stype {stype}")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        dense = onp.zeros(shape, dtype=dtype or "float32")
        data = onp.asarray(data)
        indices = onp.asarray(indices, dtype=onp.int64)
        indptr = onp.asarray(indptr, dtype=onp.int64)
        # vectorized scatter: per-nnz row ids from the indptr deltas
        rows = onp.repeat(onp.arange(shape[0]), onp.diff(indptr))
        dense[rows, indices[:len(rows)]] = data[:len(rows)]
        return CSRNDArray(array(dense, ctx=ctx, dtype=dtype)._data)
    return CSRNDArray(array(arg1, ctx=ctx, dtype=dtype)._data)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = onp.asarray(data)
        indices = onp.asarray(indices, dtype=onp.int64)
        full_shape = shape or ((int(indices.max()) + 1,) + data.shape[1:])
        dense = onp.zeros(full_shape, dtype=dtype or "float32")
        dense[indices] = data
        return RowSparseNDArray(array(dense, ctx=ctx, dtype=dtype)._data)
    return RowSparseNDArray(array(arg1, ctx=ctx, dtype=dtype)._data)


def zeros(stype, shape, ctx=None, dtype=None):
    d = _dense_zeros(shape, ctx=ctx, dtype=dtype)
    return cast_storage(d, stype)


# ---------------------------------------------------------------------
# sparse execution tier: O(nnz) compute on static-shape compressed forms
# ---------------------------------------------------------------------
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """``mx.nd.sparse.dot`` (reference dot-inl.h stype dispatch):

    * dot(csr, dense)   -> dense: padded-COO gather + contraction —
      touches O(nnz) rows of ``rhs`` (DotCsrDnsDns);
    * dot(csr.T, dense) -> row_sparse: scatter-add into the touched
      feature rows (DotCsrTDnsRsp) — the embedding/FM gradient path.
    """
    if transpose_b:
        raise MXNetError("sparse.dot: transpose_b is not supported "
                         "(reference parity)")
    if isinstance(lhs, CSRNDArray):
        cols, vals = lhs._padded()          # [B, K]
        r = rhs._data
        if not transpose_a:
            # out[b, ...] = sum_k vals[b,k] * rhs[cols[b,k], ...]
            gathered = jnp.take(r, cols, axis=0)     # [B, K, ...]
            v = vals.reshape(vals.shape + (1,) * (r.ndim - 1))
            return NDArray(jnp.sum(gathered * v.astype(r.dtype), axis=1))
        # out[f, ...] += sum over nnz at column f: vals[b,k]*rhs[b, ...]
        nrows = lhs.shape[1]
        flat_cols = cols.reshape(-1)
        contrib = (vals.reshape(vals.shape + (1,) * (r.ndim - 1))
                   .astype(r.dtype)
                   * r[:, None])                     # [B, K, ...]
        out = jnp.zeros((nrows,) + r.shape[1:], r.dtype)
        out = out.at[flat_cols].add(
            contrib.reshape((-1,) + r.shape[1:]))
        return RowSparseNDArray(out)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        a = lhs._data.T if transpose_a else lhs._data
        return NDArray(jnp.dot(a, rhs._data))
    raise MXNetError("sparse.dot: unsupported operand types")


def _lazy_rows(weight, grad):
    rows, vals = grad._compact()
    return rows, vals, weight._data


def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0):
    """Lazy row_sparse SGD (reference optimizer_op.cc SGDUpdateRspImpl):
    only the gradient's nonzero rows are gathered, updated, and
    scattered back — untouched rows are bit-identical."""
    rows, vals, w = _lazy_rows(weight, grad)
    g = vals * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    wr = jnp.take(w, rows, axis=0)
    new = wr - lr * (g + wd * wr)
    weight._adopt(w.at[rows].set(new.astype(w.dtype)))
    return weight


def adagrad_update(weight, grad, history, *, lr, epsilon=1e-7,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Lazy row_sparse AdaGrad (reference AdagradUpdateRspRspRspImpl —
    the _sparse_adagrad_update op): history rows the gradient does not
    touch are NOT decayed or written (lazy_update contract)."""
    rows, vals, w = _lazy_rows(weight, grad)
    h = history._data
    g = vals * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    hr = jnp.take(h, rows, axis=0) + jnp.square(g)
    wr = jnp.take(w, rows, axis=0) - lr * g / (jnp.sqrt(hr) + epsilon)
    history._adopt(h.at[rows].set(hr.astype(h.dtype)))
    weight._adopt(w.at[rows].set(wr.astype(w.dtype)))
    return weight
