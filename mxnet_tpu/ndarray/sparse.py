"""Sparse NDArray API surface: CSRNDArray / RowSparseNDArray.

Reference parity: python/mxnet/ndarray/sparse.py over the row_sparse/csr
storage types (include/mxnet/ndarray.h:61-65) and cast_storage
(src/operator/tensor/cast_storage.cc).

TPU-native reality (SURVEY.md §7 "hard parts"): XLA/TPU has no sparse
buffer type, so sparse arrays are *dense-backed with sparse metadata* —
the API (indices/indptr/data, retain, cast_storage) is preserved while the
math runs dense on the MXU.  This keeps sparse-using reference workloads
(sparse FM, row_sparse embeddings/optimizers) functional; the memory win
is deferred to a host-side (CPU backend) representation if ever needed.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from .ndarray import NDArray, array, invoke, zeros as _dense_zeros


class BaseSparseNDArray(NDArray):
    """Dense-backed sparse array with CACHED metadata: ``indices``/
    ``indptr``/``data`` each need a host sync to compute (VERDICT r02
    weak #5 — a silent performance cliff when accessed in a loop), so
    results are memoized against the identity of the immutable backing
    jax buffer and recomputed only after an in-place update swaps it.
    """

    __slots__ = ("_meta_cache",)

    def _adopt(self, data):
        # every in-place mutation path funnels through _adopt: drop the
        # metadata cache (no stale reads, and no pinning of the
        # pre-mutation dense buffer in memory)
        self._meta_cache = None
        super()._adopt(data)

    def _cached_meta(self, name, compute):
        store = getattr(self, "_meta_cache", None)
        if store is None:
            store = {}
            self._meta_cache = store
        if name not in store:
            store[name] = compute()
        # fresh wrapper over the (immutable) cached jax buffer: zero
        # recompute/copy cost, and caller-side __setitem__ adopts a new
        # buffer in the wrapper without touching the cache
        cached = store[name]
        return type(cached)(cached._data)


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ()

    def __init__(self, data):
        super().__init__(data, stype="csr")

    @property
    def indices(self):
        def compute():
            a = onp.asarray(self._data)
            # row-major nonzero == concatenated per-row column indices
            _, cols = onp.nonzero(a)
            return array(cols, dtype="int64")
        return self._cached_meta("indices", compute)

    @property
    def indptr(self):
        def compute():
            a = onp.asarray(self._data)
            counts = onp.count_nonzero(a, axis=1)
            return array(onp.concatenate([[0], onp.cumsum(counts)]),
                         dtype="int64")
        return self._cached_meta("indptr", compute)

    @property
    def data(self):
        def compute():
            a = onp.asarray(self._data)
            return array(a[a != 0])
        return self._cached_meta("data", compute)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        if stype == "csr":
            return self
        raise MXNetError(f"cast_storage csr->{stype} unsupported")


class RowSparseNDArray(BaseSparseNDArray):
    __slots__ = ()

    def __init__(self, data):
        super().__init__(data, stype="row_sparse")

    @property
    def indices(self):
        def compute():
            a = onp.asarray(self._data)
            nz = onp.nonzero(a.reshape(a.shape[0], -1).any(axis=1))[0]
            return array(nz, dtype="int64")
        return self._cached_meta("indices", compute)

    @property
    def data(self):
        def compute():
            a = onp.asarray(self._data)
            nz = a.reshape(a.shape[0], -1).any(axis=1)
            return array(a[nz])
        return self._cached_meta("data", compute)

    def retain(self, indices):
        idx = onp.asarray(indices._data
                          if isinstance(indices, NDArray) else indices,
                          dtype=onp.int64)
        mask = onp.zeros(self.shape[0], dtype=bool)
        mask[idx] = True
        d = jnp.where(jnp.asarray(mask).reshape((-1,) + (1,) *
                                                (self.ndim - 1)),
                      self._data, 0)
        return RowSparseNDArray(d)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        if stype == "row_sparse":
            return self
        raise MXNetError(f"cast_storage row_sparse->{stype} unsupported")


def cast_storage(arr, stype):
    if stype == "default":
        return NDArray(arr._data)
    if stype == "csr":
        return CSRNDArray(arr._data)
    if stype == "row_sparse":
        return RowSparseNDArray(arr._data)
    raise MXNetError(f"unknown stype {stype}")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        dense = onp.zeros(shape, dtype=dtype or "float32")
        data = onp.asarray(data)
        indices = onp.asarray(indices, dtype=onp.int64)
        indptr = onp.asarray(indptr, dtype=onp.int64)
        # vectorized scatter: per-nnz row ids from the indptr deltas
        rows = onp.repeat(onp.arange(shape[0]), onp.diff(indptr))
        dense[rows, indices[:len(rows)]] = data[:len(rows)]
        return CSRNDArray(array(dense, ctx=ctx, dtype=dtype)._data)
    return CSRNDArray(array(arg1, ctx=ctx, dtype=dtype)._data)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = onp.asarray(data)
        indices = onp.asarray(indices, dtype=onp.int64)
        full_shape = shape or ((int(indices.max()) + 1,) + data.shape[1:])
        dense = onp.zeros(full_shape, dtype=dtype or "float32")
        dense[indices] = data
        return RowSparseNDArray(array(dense, ctx=ctx, dtype=dtype)._data)
    return RowSparseNDArray(array(arg1, ctx=ctx, dtype=dtype)._data)


def zeros(stype, shape, ctx=None, dtype=None):
    d = _dense_zeros(shape, ctx=ctx, dtype=dtype)
    return cast_storage(d, stype)
