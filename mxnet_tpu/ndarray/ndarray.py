"""NDArray: the eager array type, backed by ``jax.Array``.

Reference parity: include/mxnet/ndarray.h:82 (``NDArray`` over a ``Chunk``
with an engine variable) and python/mxnet/ndarray/ndarray.py.  TPU-native
redesign: a ``jax.Array`` already *is* an async handle — XLA dispatch gives
the same returns-immediately semantics the reference gets from its threaded
dependency engine (src/engine/threaded_engine.cc:318), and
``block_until_ready`` is ``WaitToRead`` (threaded_engine.cc:379).  There is
no storage pool to manage: XLA owns HBM.

Mutation semantics: reference NDArrays are mutable buffers; here mutation
rebinds the wrapped functional value (``_data``), which preserves the user-
visible API (``x[:] = v``, ``x += 1``) without fighting XLA.
"""
from __future__ import annotations

import struct

import jax
import jax.numpy as jnp
import numpy as onp

from .. import _rng, autograd
from ..base import MXNetError, integer_types, numeric_types
from ..context import Context, cpu, current_context
from ..dtype import NP_TO_TYPE_FLAG, TYPE_FLAG_TO_NP, dtype_name, normalize_dtype
from ..ops.registry import OpDef, get_op

__all__ = [
    "NDArray",
    "invoke",
    "array",
    "empty",
    "zeros",
    "ones",
    "full",
    "arange",
    "linspace",
    "eye",
    "zeros_like",
    "ones_like",
    "from_jax",
    "concat",
    "concatenate",
    "stack",
    "split",
    "save",
    "load",
    "load_buffer",
    "save_buffer",
    "waitall",
]


def _ctx_of_jax_array(a) -> Context:
    try:
        dev = list(a.devices())[0]
    except Exception:
        return current_context()
    if dev.platform == "cpu" and jax.default_backend() != "cpu":
        return Context("cpu", dev.id)
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("gpu", dev.id)


class NDArray:
    __slots__ = ("_data", "_grad", "_grad_req", "_is_var", "_node", "_oidx",
                 "_stype", "_fresh_grad", "__weakref__")

    def __init__(self, data, stype="default"):
        self._data = data  # jax.Array (possibly a tracer under jit)
        self._grad = None
        self._grad_req = "null"
        self._is_var = False
        self._node = None  # autograd.TapeNode that produced this array
        self._oidx = 0
        self._stype = stype
        self._fresh_grad = False  # set by backward, cleared by Trainer

    # ------------------------------------------------------------- basics
    @property
    def shape(self):
        return tuple(int(d) for d in self._data.shape)

    @property
    def dtype(self):
        d = self._data.dtype
        return d if d == jnp.bfloat16 else onp.dtype(d)

    @property
    def size(self):
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self):
        return len(self._data.shape)

    @property
    def context(self) -> Context:
        return _ctx_of_jax_array(self._data)

    ctx = context

    @property
    def stype(self):
        return self._stype

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return self.transpose()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of an NDArray with multiple elements is ambiguous."
            )
        return bool(self._data)

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __repr__(self):
        try:
            body = str(self.asnumpy())
        except Exception:  # tracer
            body = f"<traced {self._data}>"
        return f"\n{body}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    # -------------------------------------------------------- sync points
    def asnumpy(self):
        """Blocking copy to host (reference: MXNDArraySyncCopyToCPU)."""
        a = onp.asarray(self._data)
        return a

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        """Reference: Engine::WaitForVar (threaded_engine.cc:379)."""
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    # -------------------------------------------------------- conversions
    def astype(self, dtype, copy=True):
        dtype = normalize_dtype(dtype)
        if not copy and self.dtype == dtype:
            return self
        return invoke("Cast", [self], dtype=dtype)

    def copy(self):
        return invoke("_copy", [self])

    def copyto(self, other):
        """Copy to an NDArray (writes into it) or a Context (new array)."""
        if isinstance(other, NDArray):
            other._adopt(jax.device_put(self._data, other.context.jax_device())
                         .astype(other._data.dtype))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()))
        raise TypeError(f"copyto does not support type {type(other)}")

    def as_in_context(self, context):
        if context == self.context:
            return self
        return NDArray(jax.device_put(self._data, context.jax_device()))

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse  # lazy, avoids cycle

        return sparse.cast_storage(self, stype)

    def detach(self):
        out = NDArray(self._data)
        return out

    # pickle via host numpy (optimizer-state checkpointing)
    def __getstate__(self):
        return {"data": self.asnumpy(), "stype": self._stype}

    def __setstate__(self, state):
        self._data = jnp.asarray(state["data"])
        self._grad = None
        self._grad_req = "null"
        self._is_var = False
        self._node = None
        self._oidx = 0
        self._stype = state.get("stype", "default")
        self._fresh_grad = False

    def _adopt(self, new_data):
        """In-place mutation: rebind the functional value."""
        self._data = new_data
        self._node = None

    # ---------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (reference ndarray.py attach_grad).

        grad_req='null' marks the array as a variable without allocating
        a buffer (no gradient will be written); 'add' accumulates across
        backward calls.  stype is recorded; sparse grads are
        dense-emulated (see ndarray/sparse.py).
        """
        self._grad_req = grad_req
        self._is_var = True
        if grad_req == "null":
            self._grad = None
            return
        self._grad = zeros(self.shape, dtype=self.dtype, ctx=self.context)
        if stype is not None:
            self._grad._stype = stype

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ---------------------------------------------------------- indexing
    def __getitem__(self, key):
        if isinstance(key, onp.ndarray):
            key = array(key, dtype=key.dtype)
        if isinstance(key, NDArray):
            if key.dtype == onp.bool_:
                # boolean mask: data-dependent shape -> eager only, no tape
                return NDArray(self._data[onp.asarray(key._data)])
            return invoke("take", [self, key], axis=0, mode="clip")
        key = _canonical_key(key)
        return invoke("_getitem", [self], key=key)

    def __setitem__(self, key, value):
        if isinstance(key, NDArray):
            if key.dtype == onp.bool_:
                key = onp.asarray(key._data)
            else:
                key = onp.asarray(key._data)
        else:
            key = _canonical_key(key)
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, (onp.ndarray, jnp.ndarray) + numeric_types):
            v = value
        else:
            v = onp.asarray(value)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            new = jnp.broadcast_to(jnp.asarray(v, self._data.dtype), self._data.shape)
        else:
            new = self._data.at[key].set(v)
        self._adopt(new.astype(self._data.dtype))

    # ------------------------------------------------------- shape manip
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape") is not None:
            shape = tuple(kwargs["shape"])
        return invoke("Reshape", [self], shape=shape)

    def reshape_like(self, other):
        return invoke("reshape_like", [self, other])

    # ------------------------------------------------------- arithmetic
    def __add__(self, other):
        return _binary(self, other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _binary(self, other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _binary(self, other, "broadcast_sub", "_rminus_scalar",
                       swap=True)

    def __mul__(self, other):
        return _binary(self, other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _binary(self, other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _binary(self, other, "broadcast_div", "_rdiv_scalar",
                       swap=True)

    def __mod__(self, other):
        return _binary(self, other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        return _binary(self, other, "broadcast_mod", "_rmod_scalar",
                       swap=True)

    def __pow__(self, other):
        return _binary(self, other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return _binary(self, other, "broadcast_power", "_rpower_scalar",
                       swap=True)

    def __neg__(self):
        return invoke("negative", [self])

    def __abs__(self):
        return invoke("abs", [self])

    def __matmul__(self, other):
        return invoke("_npi_matmul", [self, other])

    def __iadd__(self, other):
        self._adopt(self.__add__(other)._data)
        return self

    def __isub__(self, other):
        self._adopt(self.__sub__(other)._data)
        return self

    def __imul__(self, other):
        self._adopt(self.__mul__(other)._data)
        return self

    def __itruediv__(self, other):
        self._adopt(self.__truediv__(other)._data)
        return self

    def __eq__(self, other):
        if other is None:
            return False
        return _binary(self, other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        if other is None:
            return True
        return _binary(self, other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return _binary(self, other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return _binary(
            self, other, "broadcast_greater_equal", "_greater_equal_scalar"
        )

    def __lt__(self, other):
        return _binary(self, other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return _binary(self, other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


def _canonical_key(key):
    """Normalize an index expression to something hashable & jit-static.

    NDArray / numpy-array indices never reach here — __getitem__ routes
    them through ``take`` / boolean masking first.
    """
    if isinstance(key, list):
        key = tuple(key)
    if isinstance(key, tuple):
        return tuple(
            int(k) if isinstance(k, integer_types) else k for k in key
        )
    if isinstance(key, integer_types):
        return int(key)
    return key


def _binary(lhs, rhs, elem_op, scalar_op, swap=False):
    """Dispatch a binary dunder: NDArray rhs -> elementwise op, python
    scalar -> *_scalar op, array-like -> wrap then elementwise.  ``swap``
    marks reflected dunders (__rsub__ etc.): operand order is reversed
    for the elementwise path."""
    if isinstance(rhs, numeric_types):
        return invoke(scalar_op, [lhs], scalar=float(rhs))
    if isinstance(rhs, (onp.ndarray, list, tuple)):
        rhs = array(rhs, dtype=lhs.dtype)
    if isinstance(rhs, NDArray):
        pair = [rhs, lhs] if swap else [lhs, rhs]
        return invoke(elem_op, pair)
    raise TypeError(f"unsupported operand type {type(rhs)}")


# ============================================================== dispatcher
def _needs_grad(x):
    return isinstance(x, NDArray) and (x._is_var or x._node is not None)


def invoke(op, inputs, out=None, **params):
    """Apply a registered op to NDArrays — the single dispatch point.

    Reference parity: MXImperativeInvokeEx -> Imperative::Invoke
    (src/imperative/imperative.cc:89).  Shape/type inference, dispatch-mode
    selection and engine push all collapse into calling the op's pure JAX
    function; when autograd is recording we route through ``jax.vjp`` and
    tape the pull-back (Imperative::RecordOp, imperative.cc:193).
    """
    opdef: OpDef = get_op(op) if isinstance(op, str) else op
    global _profiler
    if _profiler is None:  # lazy: keep profiler import errors local
        from .. import profiler as _profiler_mod

        _profiler = _profiler_mod
    scope = _profiler.op_scope(opdef.name)
    if scope is not None:
        with scope:
            result = _invoke_impl(opdef, inputs, out, params)
            scope.set_result(result)  # bytes column for opstats
            return result
    return _invoke_impl(opdef, inputs, out, params)


_profiler = None
_amp = None


def _invoke_impl(opdef, inputs, out, params):
    params = {k: v for k, v in params.items() if v is not None}
    arrs = []
    nd_inputs = []
    for i in inputs:
        if isinstance(i, NDArray):
            arrs.append(i._data)
            nd_inputs.append(i)
        else:
            arrs.append(jnp.asarray(i))
            nd_inputs.append(None)
    global _amp
    if _amp is None:  # lazy: keep contrib import errors local
        from ..contrib import amp as _amp_mod

        _amp = _amp_mod
    amp_on = _amp.is_active()
    if opdef.key_param:
        params[opdef.key_param] = _rng.take_key()
    if opdef.train_param and opdef.train_param not in params:
        params[opdef.train_param] = autograd.is_training()

    nout = opdef.out_count(params)
    recording = (
        autograd.is_recording()
        and opdef.differentiable
        and any(_needs_grad(i) for i in inputs)
    )
    if recording:
        # AMP casts live INSIDE the differentiated function so vjp
        # cotangent dtypes match the tape's (uncast) primal dtypes
        def _f(*xs):
            if amp_on:
                xs = _amp.cast_inputs(opdef.name, list(xs))
            return opdef.fn(*xs, **params)

        if opdef.platform_sensitive:
            # kernel-or-jnp ops need the target platform, but jax.vjp
            # traces abstractly; pin the hint from the concrete inputs
            # around BOTH the forward trace and the later backward trace
            from ..ops import pallas_conv as _pc

            plat = _pc.platform_of(arrs)
            prev = _pc.set_trace_platform(plat)
            try:
                out_vals, raw_vjp = jax.vjp(_f, *arrs)
            finally:
                _pc.set_trace_platform(prev)

            def vjp_fn(cots, _raw=raw_vjp, _plat=plat):
                p = _pc.set_trace_platform(_plat)
                try:
                    return _raw(cots)
                finally:
                    _pc.set_trace_platform(p)
        else:
            out_vals, vjp_fn = jax.vjp(_f, *arrs)
    else:
        if amp_on:
            arrs = _amp.cast_inputs(opdef.name, arrs)
        out_vals = opdef.fn(*arrs, **params)

    single = not isinstance(out_vals, (tuple, list))
    vals = (out_vals,) if single else tuple(out_vals)
    outs = [NDArray(v) for v in vals]

    if recording:
        node = autograd.TapeNode(
            vjp_fn,
            [i if _needs_grad(i) else None for i in nd_inputs],
            [(v.shape, v.dtype) for v in vals],
            op_name=opdef.name,
            prim_fn=_f,
            all_inputs=[n if n is not None else a
                        for n, a in zip(nd_inputs, arrs)],
        )
        for i, o in enumerate(outs):
            o._node = node
            o._oidx = i

    if out is not None:
        tgt = [out] if isinstance(out, NDArray) else list(out)
        for t, o in zip(tgt, outs):
            t._adopt(o._data)
            t._node, t._oidx = o._node, o._oidx
        return out
    if single and nout == 1:
        return outs[0]
    return outs


# ============================================================== creation
def _device(ctx):
    return (ctx or current_context()).jax_device()


def array(source_array, ctx=None, dtype=None):
    """Reference semantics (python/mxnet/ndarray/utils.py array): dtype
    defaults to the source dtype for ndarray inputs, else float32."""
    from_nd = isinstance(source_array, (NDArray, onp.ndarray, jax.Array))
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = onp.asarray(source_array)
    if dtype is None:
        if not from_nd:
            dtype = onp.float32
        elif src.dtype == onp.float64:
            dtype = onp.float32  # x64 is disabled under JAX defaults
        else:
            dtype = src.dtype
    dtype = normalize_dtype(dtype)
    return NDArray(jax.device_put(src.astype(dtype), _device(ctx)))


def from_jax(a):
    return NDArray(a)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, integer_types):
        shape = (shape,)
    dtype = normalize_dtype(dtype)
    return NDArray(
        jax.device_put(jnp.zeros(shape, dtype), _device(ctx))
    )


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, integer_types):
        shape = (shape,)
    dtype = normalize_dtype(dtype)
    return NDArray(jax.device_put(jnp.ones(shape, dtype), _device(ctx)))


def full(shape, val, ctx=None, dtype=None, out=None):
    if isinstance(shape, integer_types):
        shape = (shape,)
    dtype = normalize_dtype(dtype)
    r = NDArray(jax.device_put(jnp.full(shape, val, dtype), _device(ctx)))
    if out is not None:
        out._adopt(r._data)
        return out
    return r


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    dtype = normalize_dtype(dtype)
    a = jnp.arange(start, stop, step, dtype)
    if repeat != 1:
        a = jnp.repeat(a, repeat)
    return NDArray(jax.device_put(a, _device(ctx)))


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    dtype = normalize_dtype(dtype)
    return NDArray(
        jax.device_put(jnp.linspace(start, stop, num, endpoint=endpoint,
                                    dtype=dtype), _device(ctx))
    )


def eye(N, M=0, k=0, ctx=None, dtype=None):
    dtype = normalize_dtype(dtype)
    return NDArray(
        jax.device_put(jnp.eye(N, M if M else None, k, dtype), _device(ctx))
    )


def zeros_like(data):
    return invoke("zeros_like", [data])


def ones_like(data):
    return invoke("ones_like", [data])


def concat(*data, dim=1, out=None):
    return invoke("Concat", list(data), out=out, dim=dim, num_args=len(data))


def concatenate(arrays, axis=0, always_copy=True):
    return invoke("Concat", list(arrays), dim=axis, num_args=len(arrays))


def stack(*data, axis=0, out=None):
    return invoke("stack", list(data), out=out, axis=axis, num_args=len(data))


def split(data, num_outputs, axis=1, squeeze_axis=False):
    return invoke("SliceChannel", [data], num_outputs=num_outputs, axis=axis,
                  squeeze_axis=squeeze_axis)


def waitall():
    """Reference: MXNDArrayWaitAll / Engine::WaitForAll."""
    try:
        jax.effects_barrier()
    except Exception:
        pass


# ========================================================= serialization
# Bit-compatible with the reference .params format:
#   container: src/c_api/c_api.cc:1824 (kMXAPINDArrayListMagic = 0x112)
#   per-array: src/ndarray/ndarray.cc:1590 (NDARRAY_V2_MAGIC = 0xF993fac9,
#   stype, TShape as int32 ndim + int64 dims, Context int32x2, type flag,
#   raw little-endian data)
_ND_MAGIC_V1 = 0xF993FAC8
_ND_MAGIC_V2 = 0xF993FAC9
_ND_MAGIC_V3 = 0xF993FACA
_LIST_MAGIC = 0x112


def _save_one(buf: bytearray, arr: NDArray):
    a = arr.asnumpy()
    if a.dtype == jnp.bfloat16 or str(a.dtype) == "bfloat16":
        a = a.astype(onp.float32)
    if a.dtype not in NP_TO_TYPE_FLAG:
        a = a.astype(onp.float32)
    # 0-dim arrays need the V3 (np-shape) magic: under V2 ndim==0 means
    # "none array" and the reference reader stops after the shape
    # (ndarray.cc NDArray::Load)
    buf += struct.pack("<I", _ND_MAGIC_V3 if a.ndim == 0 else _ND_MAGIC_V2)
    buf += struct.pack("<i", 0)  # kDefaultStorage
    buf += struct.pack("<i", a.ndim)
    buf += struct.pack(f"<{a.ndim}q", *a.shape)
    buf += struct.pack("<ii", 1, 0)  # Context: kCPU, id 0
    buf += struct.pack("<i", NP_TO_TYPE_FLAG[a.dtype])
    buf += onp.ascontiguousarray(a).tobytes()


class _Reader:
    def __init__(self, data):
        self.d = data
        self.o = 0

    def read(self, fmt):
        vals = struct.unpack_from(fmt, self.d, self.o)
        self.o += struct.calcsize(fmt)
        return vals if len(vals) > 1 else vals[0]

    def read_tuple(self, fmt):
        vals = struct.unpack_from(fmt, self.d, self.o)
        self.o += struct.calcsize(fmt)
        return vals

    def raw(self, n):
        b = self.d[self.o:self.o + n]
        self.o += n
        return b


def _load_one(r: _Reader, ctx=None) -> NDArray:
    magic = r.read("<I")
    if magic in (_ND_MAGIC_V2, _ND_MAGIC_V3):
        stype = r.read("<i")
        if stype not in (0,):
            raise MXNetError("loading sparse ndarrays is not supported yet")
        ndim = r.read("<i")
        shape = r.read_tuple(f"<{ndim}q") if ndim else ()
        if magic == _ND_MAGIC_V2 and ndim == 0:
            # "none" array: the record ends here (no ctx/type/data bytes)
            return zeros((), ctx=ctx)
    elif magic == _ND_MAGIC_V1:
        ndim = r.read("<I")
        shape = r.read_tuple(f"<{ndim}q") if ndim else ()
    else:
        # legacy: magic *is* ndim, dims are uint32 (ndarray.cc LegacyTShapeLoad)
        ndim = magic
        shape = r.read_tuple(f"<{ndim}I") if ndim else ()
    r.read("<ii")  # saved Context, ignored: we place on the requested ctx
    type_flag = r.read("<i")
    np_dtype = TYPE_FLAG_TO_NP[type_flag]
    n = int(onp.prod(shape)) if shape else 1
    data = onp.frombuffer(r.raw(n * np_dtype.itemsize), dtype=np_dtype)
    a = data.reshape(shape)
    return NDArray(jax.device_put(jnp.asarray(a), _device(ctx)))


def save_buffer(data) -> bytes:
    if isinstance(data, NDArray):
        arrays, keys = [data], []
    elif isinstance(data, (list, tuple)):
        arrays, keys = list(data), []
    elif isinstance(data, dict):
        keys = list(data.keys())
        arrays = [data[k] for k in keys]
    else:
        raise MXNetError("save expects NDArray, list or dict of NDArrays")
    buf = bytearray()
    buf += struct.pack("<QQ", _LIST_MAGIC, 0)
    buf += struct.pack("<Q", len(arrays))
    for a in arrays:
        _save_one(buf, a)
    buf += struct.pack("<Q", len(keys))
    for k in keys:
        kb = k.encode()
        buf += struct.pack("<Q", len(kb)) + kb
    return bytes(buf)


def save(fname, data):
    """Save NDArrays in the reference .params binary format."""
    with open(fname, "wb") as f:
        f.write(save_buffer(data))


def load_buffer(data: bytes, ctx=None):
    r = _Reader(data)
    magic, _reserved = r.read("<QQ")
    if magic != _LIST_MAGIC:
        raise MXNetError("invalid NDArray file format")
    count = r.read("<Q")
    arrays = [_load_one(r, ctx) for _ in range(count)]
    nkeys = r.read("<Q")
    if nkeys == 0:
        return arrays
    keys = []
    for _ in range(nkeys):
        klen = r.read("<Q")
        keys.append(r.raw(klen).decode())
    return dict(zip(keys, arrays))


def load(fname, ctx=None):
    """Load a reference-format .params file."""
    with open(fname, "rb") as f:
        return load_buffer(f.read(), ctx)
