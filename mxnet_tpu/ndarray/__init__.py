"""``mx.nd`` — the eager op namespace, generated from the registry.

Reference parity: python/mxnet/ndarray/register.py:116 builds Python source
per op from the C registry at import time; here the registry is native
Python so we generate closures instead.  Every registered op becomes a
module-level function taking positional NDArray inputs plus hyper-parameter
kwargs, exactly like the reference's generated wrappers.
"""
from __future__ import annotations

import inspect
import sys
import types

from ..ops import registry as _registry
from ..ops.registry import get_op, list_ops
from .ndarray import (  # noqa: F401
    NDArray,
    arange,
    array,
    concat,
    concatenate,
    empty,
    eye,
    from_jax,
    full,
    invoke,
    linspace,
    load,
    load_buffer,
    ones,
    ones_like,
    save,
    save_buffer,
    split,
    stack,
    waitall,
    zeros,
    zeros_like,
)


def _tensor_names(opdef):
    sig = inspect.signature(opdef.fn)
    names, variadic = [], False
    for p in sig.parameters.values():
        if p.kind is inspect.Parameter.POSITIONAL_OR_KEYWORD:
            names.append(p.name)
        elif p.kind is inspect.Parameter.VAR_POSITIONAL:
            variadic = True
    return names, variadic


def _make_op_func(opdef, name):
    tnames, variadic = _tensor_names(opdef)
    kw_names = [
        p.name
        for p in inspect.signature(opdef.fn).parameters.values()
        if p.kind is inspect.Parameter.KEYWORD_ONLY
    ]

    def f(*args, out=None, **kwargs):
        args = list(args)
        if args and isinstance(args[0], (list, tuple)) and variadic:
            args = list(args[0]) + args[1:]
        inputs, ki = [], 0
        import numpy as _onp

        for a in args:
            if isinstance(a, (NDArray, _onp.ndarray)) or (
                variadic and not isinstance(a, (int, float, str, bool))
            ):
                inputs.append(a)
            else:
                # positional hyper-param (reference generated wrappers
                # accept params positionally after the tensor inputs)
                while ki < len(kw_names) and kw_names[ki] in kwargs:
                    ki += 1
                kwargs[kw_names[ki]] = a
                ki += 1
        if not variadic:
            for tn in tnames[len(inputs):]:
                if tn in kwargs:
                    inputs.append(kwargs.pop(tn))
                else:
                    break
        return invoke(opdef, inputs, out=out, **kwargs)

    f.__name__ = name
    f.__qualname__ = name
    f.__doc__ = opdef.doc or f"Operator {name} (see ops registry)."
    return f


_this = sys.modules[__name__]
op = types.ModuleType("mxnet_tpu.ndarray.op")
_internal = types.ModuleType("mxnet_tpu.ndarray._internal")
sys.modules[op.__name__] = op
sys.modules[_internal.__name__] = _internal


def _expose_all():
    for name in list_ops():
        opdef = get_op(name)
        if not name.isidentifier():
            continue
        fn = _make_op_func(opdef, name)
        setattr(op, name, fn)
        if name.startswith("_"):
            setattr(_internal, name, fn)
        if not hasattr(_this, name):
            setattr(_this, name, fn)


_expose_all()


def _expose_new_ops():
    """Expose ops added after import (mx.library.load): only missing
    names are generated — existing wrapper objects stay stable."""
    for name in list_ops():
        if not name.isidentifier() or hasattr(op, name):
            continue
        opdef = get_op(name)
        fn = _make_op_func(opdef, name)
        setattr(op, name, fn)
        if name.startswith("_"):
            setattr(_internal, name, fn)
        if not hasattr(_this, name):
            setattr(_this, name, fn)


# ---------------------------------------------------------------- methods
_METHOD_OPS = [
    "sum", "nansum", "mean", "max", "min", "prod", "nanprod", "argmax",
    "argmin", "norm", "abs", "sign", "round", "rint", "fix", "floor",
    "ceil", "trunc", "sqrt", "rsqrt", "cbrt", "rcbrt", "square", "exp",
    "log", "log10", "log2", "log1p", "expm1", "sin", "cos", "tan",
    "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh", "arcsinh",
    "arccosh", "arctanh", "degrees", "radians", "reciprocal", "sigmoid",
    "relu", "softmax", "log_softmax", "clip", "expand_dims", "squeeze",
    "take", "pick", "one_hot", "topk", "sort", "argsort", "broadcast_to",
    "broadcast_like", "tile", "repeat", "pad", "flip", "slice_axis",
    "slice_like", "swapaxes", "split", "flatten", "diag",
]


def _make_method(opname):
    opdef = get_op(opname)
    kw_names = [
        p.name
        for p in inspect.signature(opdef.fn).parameters.values()
        if p.kind is inspect.Parameter.KEYWORD_ONLY
    ]

    def m(self, *args, **kwargs):
        inputs = [self]
        ai = 0
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            else:
                kwargs[kw_names[ai]] = a
                ai += 1
        return invoke(opdef, inputs, **kwargs)

    m.__name__ = opname
    return m


for _name in _METHOD_OPS:
    if not hasattr(NDArray, _name):
        setattr(NDArray, _name, _make_method(_name))


def _nd_transpose(self, *axes, **kwargs):
    kw_axes = kwargs.pop("axes", None)
    if kwargs:
        raise TypeError(
            f"transpose() got unexpected keyword arguments "
            f"{sorted(kwargs)}")
    if kw_axes is not None:  # reference kwarg form
        axes = tuple(kw_axes)
    elif len(axes) == 1 and isinstance(axes[0], (list, tuple)):
        axes = tuple(axes[0])
    return invoke("transpose", [self], axes=axes or None)


NDArray.transpose = _nd_transpose

from ..operator import custom as Custom  # noqa: E402,F401
from . import random  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import contrib  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
