"""``mx.nd.random`` namespace (reference: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .ndarray import NDArray, invoke

__all__ = ["uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "randint",
           "multinomial", "shuffle", "uniform_like", "normal_like"]


def _shape(shape):
    if shape is None:
        return (1,)
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _move(r, ctx):
    return r.as_in_context(ctx) if ctx is not None else r


def uniform(low=0, high=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    if isinstance(low, NDArray):
        return invoke("sample_uniform", [low, high], out=out, dtype=dtype,
                      shape=tuple(shape) if shape else ())
    return _move(invoke("_random_uniform", [], out=out, low=float(low),
                        high=float(high), shape=_shape(shape), dtype=dtype),
                 ctx)


def normal(loc=0, scale=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    if isinstance(loc, NDArray):
        return invoke("sample_normal", [loc, scale], out=out, dtype=dtype,
                      shape=tuple(shape) if shape else ())
    return _move(invoke("_random_normal", [], out=out, loc=float(loc),
                        scale=float(scale), shape=_shape(shape), dtype=dtype),
                 ctx)


def randn(*shape, dtype=None, ctx=None, **kw):
    loc = float(kw.get("loc", 0))
    scale = float(kw.get("scale", 1))
    return _move(invoke("_random_normal", [], loc=loc, scale=scale,
                        shape=tuple(shape) or (1,), dtype=dtype), ctx)


def gamma(alpha=1, beta=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _move(invoke("_random_gamma", [], out=out, alpha=float(alpha),
                        beta=float(beta), shape=_shape(shape), dtype=dtype),
                 ctx)


def exponential(scale=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _move(invoke("_random_exponential", [], out=out,
                        lam=1.0 / float(scale), shape=_shape(shape),
                        dtype=dtype), ctx)


def poisson(lam=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _move(invoke("_random_poisson", [], out=out, lam=float(lam),
                        shape=_shape(shape), dtype=dtype), ctx)


def negative_binomial(k=1, p=1, shape=None, dtype=None, ctx=None, out=None,
                      **kw):
    return _move(invoke("_random_negative_binomial", [], out=out, k=int(k),
                        p=float(p), shape=_shape(shape), dtype=dtype), ctx)


def generalized_negative_binomial(mu=1, alpha=1, shape=None, dtype=None,
                                  ctx=None, out=None, **kw):
    return _move(invoke("_random_generalized_negative_binomial", [], out=out,
                        mu=float(mu), alpha=float(alpha),
                        shape=_shape(shape), dtype=dtype), ctx)


def randint(low, high, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _move(invoke("_random_randint", [], out=out, low=int(low),
                        high=int(high), shape=_shape(shape), dtype=dtype),
                 ctx)


def multinomial(data, shape=(), get_prob=False, out=None, dtype="int32",
                **kw):
    return invoke("_sample_multinomial", [data], out=out, shape=shape,
                  get_prob=get_prob, dtype=dtype)


def shuffle(data, **kw):
    return invoke("_shuffle", [data])


def uniform_like(data, low=0, high=1, **kw):
    return invoke("_random_uniform_like", [data], low=low, high=high)


def normal_like(data, loc=0, scale=1, **kw):
    return invoke("_random_normal_like", [data], loc=loc, scale=scale)
