"""``mx.nd.linalg`` namespace (reference: python/mxnet/ndarray/linalg.py
over src/operator/tensor/la_op.cc)."""
from __future__ import annotations

from .ndarray import invoke


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2,
          **kw):
    return invoke("_linalg_gemm2", [A, B], transpose_a=transpose_a,
                  transpose_b=transpose_b, alpha=alpha, axis=axis)


def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
         axis=-2, **kw):
    return invoke("_linalg_gemm", [A, B, C], transpose_a=transpose_a,
                  transpose_b=transpose_b, alpha=alpha, beta=beta, axis=axis)


def potrf(A, lower=True, **kw):
    return invoke("_linalg_potrf", [A], lower=lower)


def potri(A, lower=True, **kw):
    return invoke("_linalg_potri", [A], lower=lower)


def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **kw):
    return invoke("_linalg_trsm", [A, B], transpose=transpose,
                  rightside=rightside, lower=lower, alpha=alpha)


def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **kw):
    return invoke("_linalg_trmm", [A, B], transpose=transpose,
                  rightside=rightside, lower=lower, alpha=alpha)


def syrk(A, transpose=False, alpha=1.0, **kw):
    return invoke("_linalg_syrk", [A], transpose=transpose, alpha=alpha)


def gelqf(A, **kw):
    return invoke("_linalg_gelqf", [A])


def syevd(A, **kw):
    return invoke("_linalg_syevd", [A])


def sumlogdiag(A, **kw):
    return invoke("_linalg_sumlogdiag", [A])


def extractdiag(A, offset=0, **kw):
    return invoke("_linalg_extractdiag", [A], offset=offset)


def makediag(A, offset=0, **kw):
    return invoke("_linalg_makediag", [A], offset=offset)


def inverse(A, **kw):
    return invoke("_linalg_inverse", [A])


def det(A, **kw):
    return invoke("_linalg_det", [A])


def slogdet(A, **kw):
    return invoke("_linalg_slogdet", [A])
