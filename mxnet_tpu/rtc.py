"""Runtime kernel compilation — the Pallas bridge.

Reference parity: python/mxnet/rtc.py (CudaModule/CudaKernel: compile
CUDA source with NVRTC at runtime and launch on NDArrays,
include/mxnet/rtc.h).

TPU-native substitution: the runtime-kernel mechanism on TPU is
**Pallas** — Python kernel functions compiled by Mosaic at trace time.
``PallasModule`` gives the rtc surface over it: wrap a Pallas kernel
function and launch it on NDArrays.  CUDA source strings are not
translatable; ``CudaModule`` raises with guidance.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["CudaModule", "PallasModule"]


class CudaModule:
    """Reference rtc.py:CudaModule — CUDA source has no TPU backend."""

    def __init__(self, source, options=(), exports=()):
        raise MXNetError(
            "CudaModule compiles CUDA C++ with NVRTC, which has no TPU "
            "analog; write the kernel as a Pallas function and wrap it "
            "in mxnet_tpu.rtc.PallasModule (see "
            "mxnet_tpu/ops/flash_attention.py for a full example)")


class PallasModule:
    """Launch a Pallas kernel on NDArrays (the TPU rtc).

    kernel_fn: a pallas kernel ``(in_ref..., out_ref...) -> None``.
    out_shapes: list of (shape, dtype) for the outputs.

        mod = PallasModule(my_kernel, [( (128, 128), "float32" )])
        y = mod(x)                      # NDArray in, NDArray out
    """

    def __init__(self, kernel_fn, out_shapes, grid=None, interpret=None):
        import jax

        from jax.experimental import pallas as pl

        self._kernel = kernel_fn
        self._out_shapes = [
            jax.ShapeDtypeStruct(tuple(s), d) for s, d in out_shapes]
        self._grid = grid
        if interpret is None:
            try:
                interpret = jax.default_backend() != "tpu"
            except Exception:
                interpret = True
        self._interpret = interpret
        kwargs = {"grid": grid} if grid else {}
        single = len(self._out_shapes) == 1
        self._call = jax.jit(lambda *xs: pl.pallas_call(
            kernel_fn,
            out_shape=(self._out_shapes[0] if single
                       else self._out_shapes),
            interpret=self._interpret, **kwargs)(*xs))

    def __call__(self, *inputs):
        arrs = [i._data if isinstance(i, NDArray) else i for i in inputs]
        out = self._call(*arrs)
        if isinstance(out, (list, tuple)):
            return [NDArray(o) for o in out]
        return NDArray(out)
