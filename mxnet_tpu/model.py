"""Checkpoint helpers + legacy FeedForward shim.

Reference parity: python/mxnet/model.py (``save_checkpoint`` :394,
``load_checkpoint`` :442 — the `-symbol.json` + `-NNNN.params` format —
and kvstore helpers ``_create_kvstore`` :82).
"""
from __future__ import annotations

import logging

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "BatchEndParam", "FeedForward"]


class BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True, keep_n=None):
    """Save `prefix-symbol.json` + `prefix-NNNN.params` (reference
    model.py:394), routed through the atomic versioned writer
    (resilience.checkpoint): write-to-temp + fsync + rename, a CRC32
    manifest, and a `latest` pointer — a crash mid-write can no longer
    leave a torn ``.params`` that ``load_checkpoint`` loads blindly.
    The legacy file layout is unchanged; ``keep_n`` optionally prunes
    old versions (None keeps all, the historical behavior)."""
    from .resilience.checkpoint import CheckpointManager

    CheckpointManager(prefix, keep_n=keep_n).save(
        epoch, symbol=symbol, arg_params=arg_params,
        aux_params=aux_params)
    logging.info("Saved checkpoint to \"%s-%04d.params\"", prefix,
                 epoch)


def load_params(prefix, epoch):
    """(arg_params, aux_params) from a .params file.

    When the checkpoint carries a manifest (every save since the
    atomic writer landed), the payload is CRC-verified in the SAME
    read that decodes it: a truncated/corrupt file raises instead of
    silently loading garbage weights;
    ``CheckpointManager(prefix).load()`` falls back to the previous
    good version instead."""
    from .resilience.checkpoint import CheckpointManager

    save_dict = CheckpointManager(prefix).load_params_dict(epoch)
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(symbol, arg_params, aux_params) (reference model.py:442)."""
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy pre-Module API: thin shim over Module (reference
    model.py FeedForward, deprecated even in the reference)."""

    def __init__(self, symbol, ctx=None, num_epoch=None,
                 optimizer="sgd", initializer=None, arg_params=None,
                 aux_params=None, **kwargs):
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self._kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        from . import module as mod_module

        module = mod_module.Module(
            self.symbol, context=self.ctx,
            label_names=[n for n in self.symbol.list_arguments()
                         if n.endswith("label")] or None)
        # hyper-params given to the ctor (learning_rate, momentum, wd,
        # ...) flow to the optimizer, reference FeedForward contract
        hyper = tuple(
            (k, v) for k, v in self._kwargs.items()
            if k in ("learning_rate", "momentum", "wd", "rescale_grad",
                     "clip_gradient", "beta1", "beta2", "epsilon"))
        module.fit(
            X, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer,
            optimizer_params=hyper or (("learning_rate", 0.01),),
            initializer=self.initializer,
            arg_params=self.arg_params, aux_params=self.aux_params,
            num_epoch=self.num_epoch)
        self._module = module
        self.arg_params, self.aux_params = module.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        if self._module is None:
            raise MXNetError("call fit before predict")
        out = self._module.predict(X, num_batch=num_batch, reset=reset)
        return out.asnumpy() if hasattr(out, "asnumpy") else out

    def score(self, X, eval_metric="acc", num_batch=None, **kwargs):
        """Evaluate on a data iterator (reference model.py
        FeedForward.score)."""
        if self._module is None:
            raise MXNetError("call fit before score")
        from . import metric as metric_mod

        if not hasattr(eval_metric, "update"):
            eval_metric = metric_mod.create(eval_metric)
        res = self._module.score(X, eval_metric, num_batch=num_batch)
        return res[0][1] if res else None

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})
