"""Engine properties management (reference python/mxnet/engine.py).

The reference exposes knobs on its threaded dependency engine: bulk-size
(how many small ops fuse into one engine segment).  On TPU the XLA
runtime owns scheduling — `jax.jit` IS the bulking mechanism — so these
calls keep the reference API and record the requested value, but the
actual fusion decisions belong to the compiler.
"""
from __future__ import annotations

import contextlib

__all__ = ["set_bulk_size", "get_bulk_size", "bulk"]

_BULK_SIZE = 15  # reference default MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN


def set_bulk_size(size):
    """Set size limit on bulk execution (reference engine.py:26).

    Returns the previous value.  No-op for execution on TPU: XLA fuses
    whole jitted programs regardless.
    """
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


def get_bulk_size():
    return _BULK_SIZE


@contextlib.contextmanager
def bulk(size):
    """Scoped bulk-size override (reference engine.py bulk())."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
