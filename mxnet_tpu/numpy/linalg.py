"""mx.np.linalg (reference: python/mxnet/numpy/linalg.py over
src/operator/numpy/linalg/).

Factorizations route through jnp.linalg inside registered ops so
autograd tapes them where jax defines gradients.
"""
from __future__ import annotations

from .multiarray import _f

__all__ = ["norm", "svd", "cholesky", "qr", "inv", "pinv", "det",
           "slogdet", "solve", "eigh", "eigvalsh", "matrix_rank",
           "matrix_power", "multi_dot", "lstsq", "tensorinv",
           "tensorsolve"]


def norm(x, ord=None, axis=None, keepdims=False):  # noqa: A002
    return _f("_npi_norm", x, ord=ord, axis=axis, keepdims=keepdims)


def svd(a):
    return _f("_npi_svd", a)


def cholesky(a):
    return _f("_npi_cholesky", a)


def qr(a):
    return _f("_npi_qr", a)


def inv(a):
    return _f("_npi_inv", a)


def pinv(a, rcond=1e-15):
    return _f("_npi_pinv", a, rcond=rcond)


def det(a):
    return _f("_npi_det", a)


def slogdet(a):
    return _f("_npi_slogdet", a)


def solve(a, b):
    return _f("_npi_solve", a, b)


def eigh(a):
    return _f("_npi_eigh", a)


def eigvalsh(a):
    return _f("_npi_eigvalsh", a)


def matrix_rank(a, tol=None):
    return _f("_npi_matrix_rank", a, tol=tol)


def matrix_power(a, n):
    return _f("_npi_matrix_power", a, n=n)


def multi_dot(arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = _f("_npi_dot", out, a)
    return out


def lstsq(a, b, rcond=None):
    return _f("_npi_lstsq", a, b, rcond=rcond)


def tensorinv(a, ind=2):
    return _f("_npi_tensorinv", a, ind=ind)


def tensorsolve(a, b, axes=None):
    return _f("_npi_tensorsolve", a, b, axes=axes)
