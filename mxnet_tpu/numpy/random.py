"""mx.np.random (reference: python/mxnet/numpy/random.py over
src/operator/numpy/random/)."""
from __future__ import annotations

from .. import random as _random
from ..ndarray.ndarray import invoke
from .multiarray import _np

__all__ = ["uniform", "normal", "randn", "rand", "randint", "choice",
           "shuffle", "gamma", "exponential", "beta", "multinomial",
           "seed"]


def seed(s):
    _random.seed(s)


def _shape(size):
    if size is None:
        return (1,)
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    out = _np(invoke("_random_uniform", [], low=float(low),
                     high=float(high), shape=_shape(size), dtype=dtype))
    return out if size is not None else _np(out.reshape(()))


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    out = _np(invoke("_random_normal", [], loc=float(loc),
                     scale=float(scale), shape=_shape(size), dtype=dtype))
    return out if size is not None else _np(out.reshape(()))


def randn(*size):
    return normal(size=size or None)


def rand(*size):
    return uniform(size=size or None)


def randint(low, high=None, size=None, dtype=None, ctx=None):
    if high is None:  # numpy one-arg form: sample [0, low)
        low, high = 0, low
    return _np(invoke("_random_randint", [], low=low, high=high,
                      shape=_shape(size), dtype=dtype or "int32"))


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None):
    return _np(invoke("_random_gamma", [], alpha=float(shape),
                      beta=float(scale), shape=_shape(size), dtype=dtype))


def exponential(scale=1.0, size=None, ctx=None):
    return _np(invoke("_random_exponential", [], lam=1.0 / float(scale),
                      shape=_shape(size)))


def beta(a, b, size=None, dtype=None, ctx=None):
    # beta(a,b) = ga/(ga+gb) with ga~Gamma(a,1), gb~Gamma(b,1)
    ga = invoke("_random_gamma", [], alpha=float(a), beta=1.0,
                shape=_shape(size))
    gb = invoke("_random_gamma", [], alpha=float(b), beta=1.0,
                shape=_shape(size))
    return _np(ga / (ga + gb))


def multinomial(n, pvals, size=None):
    import numpy as onp

    out = onp.random.multinomial(n, onp.asarray(pvals), size=size)
    from .multiarray import array

    return array(out, dtype="int64")


def choice(a, size=None, replace=True, p=None, ctx=None):
    import numpy as onp

    if hasattr(a, "asnumpy"):
        a = a.asnumpy()
    out = onp.random.choice(a, size=size, replace=replace,
                            p=onp.asarray(p) if p is not None else None)
    from .multiarray import array

    return array(out)


def shuffle(x):
    out = invoke("_shuffle", [x])
    x._adopt(out._data)
