"""mx.np ndarray and function namespace.

Reference parity: python/mxnet/numpy/multiarray.py (268 defs) over the
src/operator/numpy/ op set.  The np ndarray subclasses the core NDArray
(same jax.Array payload, same autograd tape) and differs in semantics:
numpy-style operators and dtype promotion, boolean indexing, zero-dim
arrays from reductions, and numpy-style repr.  Every differentiable
function routes through the op registry so ``autograd.record`` tapes it.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as onp

from .. import ndarray as _nd
from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, invoke

class ndarray(NDArray):
    """numpy-semantics array (reference numpy/multiarray.py:ndarray)."""

    __slots__ = ()

    def __repr__(self):
        try:
            r = repr(self.asnumpy())
            return r if r.startswith("array(") else f"array({r})"
        except Exception:
            return f"array(<traced {self._data}>)"

    def __getitem__(self, key):
        out = super().__getitem__(key)
        return _np(out)

    def asnumpy(self):
        return onp.asarray(self._data)

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    @property
    def T(self):
        return _np(super().transpose())

    def astype(self, dtype, copy=True):
        return _np(super().astype(dtype, copy=copy))

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return _np(invoke("Reshape", [self], shape=shape))

    def flatten(self, order="C"):
        return self.reshape((-1,))

    def as_nd_ndarray(self):
        """Drop to the classic nd interface (reference
        multiarray.py:as_nd_ndarray)."""
        out = NDArray(self._data)
        out._node, out._oidx = self._node, self._oidx
        out._is_var, out._grad = self._is_var, self._grad
        return out

    def as_np_ndarray(self):
        return self


def _np(a):
    """Re-type an NDArray (or raw array) as np.ndarray, preserving the
    autograd linkage."""
    if isinstance(a, ndarray):
        return a
    if isinstance(a, NDArray):
        out = ndarray(a._data)
        out._node, out._oidx = a._node, a._oidx
        out._is_var, out._grad = a._is_var, a._grad
        out._grad_req = a._grad_req
        return out
    return ndarray(jnp.asarray(a))


def _wrap_dunders():
    names = [
        "__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
        "__rmul__", "__truediv__", "__rtruediv__", "__mod__", "__rmod__",
        "__pow__", "__rpow__", "__floordiv__", "__rfloordiv__",
        "__neg__", "__abs__", "__matmul__",
    ]
    for name in names:
        base = getattr(NDArray, name, None)
        if base is None:
            continue

        def make(meth):
            def f(self, *args):
                out = meth(self, *args)
                return _np(out) if isinstance(out, NDArray) else out

            f.__name__ = meth.__name__
            return f

        setattr(ndarray, name, make(base))


_wrap_dunders()


def _add_cmp_dunders():
    # numpy semantics: comparisons yield BOOL arrays (the classic nd
    # interface returns 1.0/0.0 floats, matching the reference split
    # between mx.nd and mx.np); non-differentiable, so no tape needed
    for name, fn in [("__eq__", jnp.equal), ("__ne__", jnp.not_equal),
                     ("__lt__", jnp.less), ("__le__", jnp.less_equal),
                     ("__gt__", jnp.greater),
                     ("__ge__", jnp.greater_equal)]:
        def make(fn):
            def f(self, other):
                o = other._data if isinstance(other, NDArray) else other
                return ndarray(fn(self._data, o))

            return f

        setattr(ndarray, name, make(fn))
    ndarray.__hash__ = None


_add_cmp_dunders()


def _in(x):
    """Coerce a function argument to something invoke accepts."""
    if isinstance(x, NDArray):
        return x
    return ndarray(jnp.asarray(x))


def _f(op, *inputs, **params):
    """Invoke a registered op, np-typing the output(s)."""
    out = invoke(op, [_in(i) for i in inputs], **params)
    if isinstance(out, (list, tuple)):
        return tuple(_np(o) for o in out)
    return _np(out)


def _direct(fn, *arrays, **kw):
    """Non-differentiable direct jnp call (logic/int ops — no tape)."""
    vals = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
            for a in arrays]
    out = fn(*vals, **kw)
    if isinstance(out, (list, tuple)):
        return tuple(ndarray(o) for o in out)
    return ndarray(out)


# ------------------------------------------------------------- creation
def array(obj, dtype=None, ctx=None):
    if isinstance(obj, NDArray):
        data = obj._data
        if dtype is not None:
            data = data.astype(dtype)
        return ndarray(data)
    return _np(_nd.array(obj, dtype=dtype or "float32",
                         ctx=ctx or current_context()))


def zeros(shape, dtype="float32", ctx=None, order="C"):
    return _np(_nd.zeros(shape, ctx=ctx, dtype=dtype))


def ones(shape, dtype="float32", ctx=None, order="C"):
    return _np(_nd.ones(shape, ctx=ctx, dtype=dtype))


def full(shape, fill_value, dtype=None, ctx=None, order="C"):
    return _np(_nd.full(shape, fill_value, ctx=ctx,
                        dtype=dtype or "float32"))


def empty(shape, dtype="float32", ctx=None, order="C"):
    return zeros(shape, dtype=dtype, ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return _np(_nd.arange(start, stop, step, dtype=dtype or "float32",
                          ctx=ctx))


def linspace(start, stop, num=50, endpoint=True, retstep=False,
             dtype=None, axis=0, ctx=None):
    out = _np(_nd.linspace(start, stop, num, endpoint=endpoint,
                           dtype=dtype or "float32", ctx=ctx))
    if retstep:
        step = (stop - start) / (num - 1 if endpoint else num)
        return out, step
    return out


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    return _direct(jnp.logspace, start, stop, num=num, endpoint=endpoint,
                   base=base, dtype=dtype or "float32")


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    return _np(_nd.eye(N, M, k, dtype=dtype, ctx=ctx))


def identity(n, dtype="float32", ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def zeros_like(a, dtype=None):
    return _f("zeros_like", a) if dtype is None else \
        _direct(jnp.zeros_like, a, dtype=dtype)


def ones_like(a, dtype=None):
    return _f("ones_like", a) if dtype is None else \
        _direct(jnp.ones_like, a, dtype=dtype)


def full_like(a, fill_value, dtype=None):
    return _f("_npi_full_like", a, fill_value=fill_value, dtype=dtype)


def copy(a):
    return _f("_copy", a)


def tri(N, M=None, k=0, dtype="float32", ctx=None):
    return _f("_npi_tri", N=N, M=M, k=k, dtype=dtype)


def meshgrid(*xi, indexing="xy"):
    return list(_f("_npi_meshgrid", *xi, num_args=len(xi),
                   indexing=indexing))


def indices(dimensions, dtype="int32", ctx=None):
    return _f("_npi_indices", dimensions=tuple(dimensions), dtype=dtype)


# --------------------------------------------------------------- unary
_UNARY = {
    "sin": "sin", "cos": "cos", "tan": "tan", "arcsin": "arcsin",
    "arccos": "arccos", "arctan": "arctan", "sinh": "sinh",
    "cosh": "cosh", "tanh": "tanh", "arcsinh": "arcsinh",
    "arccosh": "arccosh", "arctanh": "arctanh", "exp": "exp",
    "expm1": "expm1", "log": "log", "log2": "log2", "log10": "log10",
    "log1p": "log1p", "sqrt": "sqrt", "cbrt": "cbrt", "square": "square",
    "absolute": "abs", "abs": "abs", "fabs": "abs", "sign": "sign",
    "floor": "floor", "ceil": "ceil", "trunc": "trunc", "rint": "rint",
    "fix": "fix", "negative": "negative", "reciprocal": "reciprocal",
    "degrees": "degrees", "radians": "radians", "sigmoid": "sigmoid",
}


def _make_unary(npname, opname):
    def f(x, out=None, **kwargs):
        return _f(opname, x)

    f.__name__ = npname
    f.__doc__ = f"numpy-semantics {npname} (op {opname})."
    return f


for _npname, _opname in _UNARY.items():
    globals()[_npname] = _make_unary(_npname, _opname)


def around(a, decimals=0):
    if decimals == 0:
        return _f("round", a)
    factor = 10.0 ** decimals
    return _np((_f("round", _in(a) * factor)) / factor)


round_ = around


# -------------------------------------------------------------- binary
_BINARY = {
    "add": "broadcast_add", "subtract": "broadcast_sub",
    "multiply": "broadcast_mul", "divide": "broadcast_div",
    "power": "broadcast_power", "maximum": "broadcast_maximum",
    "minimum": "broadcast_minimum", "hypot": "broadcast_hypot",
    "arctan2": "arctan2", "mod": "broadcast_mod",
    "remainder": "broadcast_mod",
    "true_divide": "_npi_true_divide",
    "floor_divide": "_npi_floor_divide", "fmod": "_npi_fmod",
    "copysign": "_npi_copysign", "heaviside": "_npi_heaviside",
    "ldexp": "_npi_ldexp", "cross": "_npi_cross",
}


def _make_binary(npname, opname):
    def f(x1, x2, out=None, **kwargs):
        return _f(opname, x1, x2)

    f.__name__ = npname
    return f


for _npname, _opname in _BINARY.items():
    globals()[_npname] = _make_binary(_npname, _opname)


# ---------------------------------------------------------- comparisons
def _make_cmp(npname, fn):
    def f(x1, x2):
        return _direct(fn, x1, x2)

    f.__name__ = npname
    return f


for _npname, _fn in [
    ("equal", jnp.equal), ("not_equal", jnp.not_equal),
    ("greater", jnp.greater), ("greater_equal", jnp.greater_equal),
    ("less", jnp.less), ("less_equal", jnp.less_equal),
]:
    globals()[_npname] = _make_cmp(_npname, _fn)


def logical_and(x1, x2):
    return _f("_npi_logical_and", x1, x2)


def logical_or(x1, x2):
    return _f("_npi_logical_or", x1, x2)


def logical_xor(x1, x2):
    return _f("_npi_logical_xor", x1, x2)


def logical_not(x):
    return _direct(jnp.logical_not, x)


# ------------------------------------------------------------ reductions
def sum(a, axis=None, dtype=None, keepdims=False):  # noqa: A001
    return _f("sum", a, axis=axis, keepdims=keepdims)


def mean(a, axis=None, dtype=None, keepdims=False):
    return _f("mean", a, axis=axis, keepdims=keepdims)


def prod(a, axis=None, keepdims=False):
    return _f("prod", a, axis=axis, keepdims=keepdims)


def max(a, axis=None, keepdims=False):  # noqa: A001
    return _f("max", a, axis=axis, keepdims=keepdims)


def min(a, axis=None, keepdims=False):  # noqa: A001
    return _f("min", a, axis=axis, keepdims=keepdims)


def amax(a, axis=None, keepdims=False):
    return max(a, axis=axis, keepdims=keepdims)


def amin(a, axis=None, keepdims=False):
    return min(a, axis=axis, keepdims=keepdims)


def std(a, axis=None, ddof=0, keepdims=False):
    return _f("_npi_std", a, axis=axis, ddof=ddof, keepdims=keepdims)


def var(a, axis=None, ddof=0, keepdims=False):
    return _f("_npi_var", a, axis=axis, ddof=ddof, keepdims=keepdims)


def average(a, axis=None, weights=None, returned=False):
    if weights is None:
        out = mean(a, axis=axis)
        return (out, None) if returned else out
    return _f("_npi_average", a, weights, axis=axis, returned=returned)


def median(a, axis=None, keepdims=False):
    return _f("_npi_median", a, axis=axis, keepdims=keepdims)


def percentile(a, q, axis=None, interpolation="linear", keepdims=False):
    return _f("_npi_percentile", a, q=q, axis=axis,
              interpolation=interpolation, keepdims=keepdims)


def quantile(a, q, axis=None, interpolation="linear", keepdims=False):
    return _f("_npi_quantile", a, q=q, axis=axis,
              interpolation=interpolation, keepdims=keepdims)


def cumsum(a, axis=None, dtype=None):
    return _f("cumsum", a, axis=axis, dtype=dtype)


def cumprod(a, axis=None, dtype=None):
    return _f("_npi_cumprod", a, axis=axis, dtype=dtype)


def argmax(a, axis=None, keepdims=False):
    return _f("argmax", a, axis=axis, keepdims=keepdims)


def argmin(a, axis=None, keepdims=False):
    return _f("argmin", a, axis=axis, keepdims=keepdims)


def all(a, axis=None, keepdims=False):  # noqa: A001
    return _direct(jnp.all, a, axis=axis, keepdims=keepdims)


def any(a, axis=None, keepdims=False):  # noqa: A001
    return _direct(jnp.any, a, axis=axis, keepdims=keepdims)


def count_nonzero(a, axis=None):
    return _direct(jnp.count_nonzero, a, axis=axis)


def clip(a, a_min, a_max):
    return _f("clip", a, a_min=a_min, a_max=a_max)


# ------------------------------------------------------------ contraction
def dot(a, b, out=None):
    return _f("_npi_dot", a, b)


def matmul(a, b):
    return _f("_npi_matmul", a, b)


def einsum(subscripts, *operands, optimize=True):
    return _f("_npi_einsum", *operands, subscripts=subscripts,
              optimize=optimize)


def tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        a_ax, b_ax = axes
        a_ax = (a_ax,) if isinstance(a_ax, int) else tuple(a_ax)
        b_ax = (b_ax,) if isinstance(b_ax, int) else tuple(b_ax)
        return _f("_npi_tensordot", a, b, a_axes_summed=a_ax,
                  b_axes_summed=b_ax)
    return _f("_npi_tensordot", a, b, axes=int(axes))


def vdot(a, b):
    return _f("_npi_vdot", a, b)


def inner(a, b):
    return _f("_npi_inner", a, b)


def outer(a, b):
    return _f("_npi_outer", a, b)


def kron(a, b):
    return _f("_npi_kron", a, b)


def trace(a, offset=0, axis1=0, axis2=1):
    return _f("_npi_trace", a, offset=offset, axis1=axis1, axis2=axis2)


def tril(m, k=0):
    return _f("_npi_tril", m, k=k)


def triu(m, k=0):
    return _f("_npi_triu", m, k=k)


# ------------------------------------------------------------ shape ops
def reshape(a, newshape, order="C"):
    return _f("Reshape", a, shape=tuple(newshape)
              if isinstance(newshape, (list, tuple)) else (newshape,))


def transpose(a, axes=None):
    return _f("transpose", a, axes=tuple(axes) if axes else None)


def swapaxes(a, axis1, axis2):
    return _f("SwapAxis", a, dim1=axis1, dim2=axis2)


def moveaxis(a, source, destination):
    return _f("_npi_moveaxis", a, source=source, destination=destination)


def rollaxis(a, axis, start=0):
    return _f("_npi_rollaxis", a, axis=axis, start=start)


def expand_dims(a, axis):
    return _f("expand_dims", a, axis=axis)


def squeeze(a, axis=None):
    return _f("_npi_squeeze", a, axis=axis)


def concatenate(seq, axis=0, out=None):
    return _f("Concat", *seq, dim=axis or 0, num_args=len(seq))


def stack(arrays, axis=0, out=None):
    return _f("stack", *arrays, axis=axis, num_args=len(arrays))


def hstack(tup):
    return _f("_npi_hstack", *tup, num_args=len(tup))


def vstack(tup):
    return _f("_npi_vstack", *tup, num_args=len(tup))


def dstack(tup):
    return _f("_npi_dstack", *tup, num_args=len(tup))


def column_stack(tup):
    return _f("_npi_column_stack", *tup, num_args=len(tup))


def split(ary, indices_or_sections, axis=0):
    a = _in(ary)
    n = a.shape[axis]
    if isinstance(indices_or_sections, int):
        if n % indices_or_sections:
            raise MXNetError("array split does not result in an equal "
                             "division")
        out = _f("split", a, num_outputs=indices_or_sections, axis=axis)
    else:
        pieces = []
        prev = 0
        bounds = list(indices_or_sections) + [n]
        for b in bounds:
            b = n if b > n else int(b)
            pieces.append(_np(invoke(
                "slice_axis", [a], axis=axis, begin=prev, end=b)))
            prev = b
            if b >= n:
                break
        return pieces
    return list(out) if isinstance(out, tuple) else [out]


def array_split(ary, indices_or_sections, axis=0):
    a = _in(ary)
    n = a.shape[axis]
    if isinstance(indices_or_sections, int):
        k = indices_or_sections
        sizes = [n // k + (1 if i < n % k else 0) for i in range(k)]
        bounds = []
        acc = 0
        for s in sizes[:-1]:
            acc += s
            bounds.append(acc)
        return split(ary, bounds, axis=axis)
    return split(ary, indices_or_sections, axis=axis)


def hsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=1)


def vsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=0)


def tile(a, reps):
    return _f("tile", a, reps=tuple(reps) if isinstance(
        reps, (list, tuple)) else (reps,))


def repeat(a, repeats, axis=None):
    return _f("repeat", a, repeats=repeats, axis=axis)


def flip(a, axis=None):
    if axis is None:
        out = _in(a)
        for ax in range(out.ndim):
            out = invoke("flip", [out], axis=ax)
        return _np(out)
    return _f("flip", a, axis=axis)


def flipud(a):
    return _f("_npi_flipud", a)


def fliplr(a):
    return _f("_npi_fliplr", a)


def roll(a, shift, axis=None):
    return _f("_npi_roll", a, shift=shift, axis=axis)


def rot90(m, k=1, axes=(0, 1)):
    return _f("_npi_rot90", m, k=k, axes=tuple(axes))


def ravel(a, order="C"):
    return reshape(a, (-1,))


def broadcast_to(a, shape):
    return _f("broadcast_to", a, shape=tuple(shape))


def broadcast_arrays(*args):
    shape = onp.broadcast_shapes(*[tuple(_in(a).shape) for a in args])
    return [broadcast_to(a, shape) for a in args]


def atleast_1d(*arys):
    out = [_np(invoke("Reshape", [_in(a)], shape=(1,)))
           if _in(a).ndim == 0 else _np(_in(a)) for a in arys]
    return out[0] if len(out) == 1 else out


def atleast_2d(*arys):
    out = []
    for a in arys:
        a = _in(a)
        while a.ndim < 2:
            a = invoke("expand_dims", [a], axis=0)
        out.append(_np(a))
    return out[0] if len(out) == 1 else out


def atleast_3d(*arys):
    out = []
    for a in arys:
        a = _in(a)
        while a.ndim < 3:
            a = invoke("expand_dims", [a], axis=a.ndim)
        out.append(_np(a))
    return out[0] if len(out) == 1 else out


# -------------------------------------------------------- search & sort
def sort(a, axis=-1, kind=None, order=None):
    return _f("sort", a, axis=axis)


def argsort(a, axis=-1, kind=None, order=None):
    return _f("argsort", a, axis=axis)


def unique(ar, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    out = _f("_npi_unique", ar, return_index=return_index,
             return_inverse=return_inverse, return_counts=return_counts,
             axis=axis)
    return out


def nonzero(a):
    mat = _f("_npi_nonzero", a)
    return tuple(_np(mat[:, i]) for i in range(_in(a).ndim or 1))


def flatnonzero(a):
    return nonzero(ravel(a))[0]


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    return _f("where", condition, x, y)


def searchsorted(a, v, side="left"):
    return _f("_npi_searchsorted", a, v, side=side)


def digitize(x, bins, right=False):
    return _f("_npi_digitize", x, bins, right=right)


def bincount(x, weights=None, minlength=0):
    if weights is None:
        return _direct(jnp.bincount, _in(x)._data.astype(jnp.int32),
                       minlength=minlength)
    return _f("_npi_bincount", x, weights, minlength=minlength)


def histogram(a, bins=10, range=None):  # noqa: A002
    h, e = _f("_npi_histogram", a, bins=bins, range=range)
    return h, e


def take(a, indices, axis=None, mode="clip"):
    if axis is None:
        return _f("take", ravel(a), indices, axis=0, mode=mode)
    return _f("take", a, indices, axis=axis, mode=mode)


def diag(v, k=0):
    return _f("diag", v, k=k)


def diff(a, n=1, axis=-1):
    return _f("_npi_diff", a, n=n, axis=axis)


def ediff1d(ary, to_end=None, to_begin=None):
    return _f("_npi_ediff1d", ary, to_end=to_end, to_begin=to_begin)


def interp(x, xp, fp, left=None, right=None):
    return _f("_npi_interp", x, xp, fp, left=left, right=right)


def polyval(p, x):
    return _f("_npi_polyval", p, x)


# ------------------------------------------------------------ logic ops
def isnan(x):
    return _f("_npi_isnan", x)


def isinf(x):
    return _f("_npi_isinf", x)


def isfinite(x):
    return _f("_npi_isfinite", x)


def isposinf(x):
    return _f("_npi_isposinf", x)


def isneginf(x):
    return _f("_npi_isneginf", x)


def nan_to_num(x, copy=True, nan=0.0, posinf=None, neginf=None):
    return _f("_npi_nan_to_num", x, nan=nan, posinf=posinf, neginf=neginf)


def array_equal(a1, a2):
    return bool(_f("_npi_array_equal", a1, a2).item())


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return bool(_f("_contrib_allclose", a, b, rtol=rtol, atol=atol,
                   equal_nan=equal_nan).item())


def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _direct(jnp.isclose, a, b, rtol=rtol, atol=atol,
                   equal_nan=equal_nan)


def may_share_memory(a, b, max_work=None):
    return False


def shares_memory(a, b, max_work=None):
    return False


# --------------------------------------------------------------- windows
def hanning(M, dtype="float32", ctx=None):
    return _f("_npi_hanning", M=M, dtype=dtype)


def hamming(M, dtype="float32", ctx=None):
    return _f("_npi_hamming", M=M, dtype=dtype)


def blackman(M, dtype="float32", ctx=None):
    return _f("_npi_blackman", M=M, dtype=dtype)


# ------------------------------------------------------------- misc math
def maximum_(x1, x2):
    return _f("broadcast_maximum", x1, x2)


def deg2rad(x):
    return _f("_npi_deg2rad", x)


def rad2deg(x):
    return _f("_npi_rad2deg", x)


def lcm(x1, x2):
    return _f("_npi_lcm", x1, x2)


def gcd(x1, x2):
    return _f("_npi_gcd", x1, x2)


def frexp(x):
    return _f("_npi_frexp", x)


def insert(arr, obj, values, axis=None):
    return _f("_npi_insert", arr, values, obj=obj, axis=axis)


def delete(arr, obj, axis=None):
    return _f("_npi_delete", arr, obj=obj, axis=axis)


def resize(a, new_shape):
    return _f("_npi_resize", a, new_shape=tuple(new_shape)
              if isinstance(new_shape, (list, tuple)) else (new_shape,))


def corrcoef(x):
    return _f("_npi_corrcoef", x)


def pad(array, pad_width, mode="constant", constant_values=0):  # noqa: A002
    a = _in(array)
    if isinstance(pad_width, int):
        pad_width = [(pad_width, pad_width)] * a.ndim
    return _direct(jnp.pad, a, pad_width=tuple(tuple(p) for p in
                                               pad_width), mode=mode,
                   **({"constant_values": constant_values}
                      if mode == "constant" else {}))


# constants
pi = onp.pi
e = onp.e
euler_gamma = onp.euler_gamma
inf = onp.inf
nan = onp.nan
newaxis = None
float32 = onp.float32
float64 = onp.float64
float16 = onp.float16
int32 = onp.int32
int64 = onp.int64
int8 = onp.int8
uint8 = onp.uint8
bool_ = onp.bool_
bfloat16 = jnp.bfloat16
_np_version = onp.__version__


# ---------------------------------------------------------- round 3 fill
# (reference multiarray.py tail + numpy_dispatch_protocol.py interop)
def empty_like(a, dtype=None):
    return zeros_like(a, dtype=dtype)


def geomspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    return _direct(jnp.geomspace, start, stop, num=num, endpoint=endpoint,
                   dtype=dtype or "float32")


def round(a, decimals=0):  # noqa: A001
    return _f("round", a) if decimals == 0 else \
        _direct(jnp.round, a, decimals=decimals)


def fmax(x1, x2):
    return _direct(jnp.fmax, x1, x2)


def fmin(x1, x2):
    return _direct(jnp.fmin, x1, x2)


def nansum(a, axis=None, dtype=None, keepdims=False):
    return _direct(jnp.nansum, a, axis=axis, dtype=dtype,
                   keepdims=keepdims)


def nanprod(a, axis=None, dtype=None, keepdims=False):
    return _direct(jnp.nanprod, a, axis=axis, dtype=dtype,
                   keepdims=keepdims)


def nanargmax(a, axis=None):
    return _direct(jnp.nanargmax, a, axis=axis)


def nanargmin(a, axis=None):
    return _direct(jnp.nanargmin, a, axis=axis)


def flatten(a, order="C"):
    return _in(a).reshape((-1,))


def dsplit(ary, indices_or_sections):
    return [_np(o) for o in
            _direct(jnp.dsplit, ary, indices_or_sections)]


def argwhere(a):
    return _direct(jnp.argwhere, a)


def extract(condition, arr):
    a = _in(arr)
    c = _in(condition)
    return _direct(lambda aa, cc: aa.ravel()[jnp.nonzero(cc.ravel())[0]],
                   a, c)


def partition(a, kth, axis=-1):
    return _direct(jnp.partition, a, kth=kth, axis=axis)


def argpartition(a, kth, axis=-1):
    return _direct(jnp.argpartition, a, kth=kth, axis=axis)


def take_along_axis(arr, indices, axis):
    return _f("_npi_take_along_axis", arr, indices, axis=axis)


def choose(a, choices):
    ch = stack([_in(c) for c in choices]) if isinstance(choices, (list, tuple)) \
        else _in(choices)
    return take_along_axis(ch, _in(a).astype("int64").reshape(
        (1,) + tuple(_in(a).shape)), axis=0)[0]


def compress(condition, a, axis=None):
    return _direct(
        lambda cc, aa: jnp.compress(onp.asarray(cc).astype(bool), aa,
                                    axis=axis),
        condition, a)


def append(arr, values, axis=None):
    return _f("_npi_concatenate", arr, values, axis=axis) if axis is not None \
        else _f("_npi_concatenate", _in(arr).reshape((-1,)),
                _in(values).reshape((-1,)), axis=0)


def array_equiv(a1, a2):
    try:
        return bool(_direct(
            lambda a, b: jnp.all(jnp.broadcast_arrays(a, b)[0]
                                 == jnp.broadcast_arrays(a, b)[1]),
            a1, a2).item())
    except ValueError:
        return False


def bartlett(M, dtype="float32", ctx=None):
    return _f("_npi_bartlett", M=M, dtype=dtype)


def kaiser(M, beta, dtype="float32", ctx=None):
    return _direct(lambda: jnp.asarray(onp.kaiser(M, beta), dtype=dtype))


def diagonal(a, offset=0, axis1=0, axis2=1):
    return _f("_npi_diagonal", a, offset=offset, axis1=axis1, axis2=axis2)


def diagflat(v, k=0):
    return _f("_npi_diagflat", v, k=k)


def diag_indices_from(arr):
    n = _in(arr).shape[0]
    idx = arange(n, dtype="int64")
    return tuple(idx for _ in range(_in(arr).ndim))


def triu_indices(n, k=0, m=None):
    r, c = onp.triu_indices(n, k, m)
    return (array(r, dtype="int64"), array(c, dtype="int64"))


def tril_indices(n, k=0, m=None):
    r, c = onp.tril_indices(n, k, m)
    return (array(r, dtype="int64"), array(c, dtype="int64"))


def triu_indices_from(arr, k=0):
    s = _in(arr).shape
    return triu_indices(s[-2], k, s[-1])


def tril_indices_from(arr, k=0):
    s = _in(arr).shape
    return tril_indices(s[-2], k, s[-1])


def ndim(a):
    return _in(a).ndim


def shape(a):
    return tuple(_in(a).shape)


def size(a, axis=None):
    s = _in(a).shape
    if axis is None:
        out = 1
        for d in s:
            out *= d
        return out
    return s[axis]


def asarray(a, dtype=None):
    if isinstance(a, ndarray) and dtype is None:
        return a
    return array(a, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return asarray(a, dtype=dtype)


def float_power(x1, x2):
    return _direct(jnp.float_power, x1, x2)


def bitwise_and(x1, x2):
    return _f("_npi_bitwise_and", x1, x2)


def bitwise_or(x1, x2):
    return _f("_npi_bitwise_or", x1, x2)


def bitwise_xor(x1, x2):
    return _f("_npi_bitwise_xor", x1, x2)


def bitwise_not(x):
    return _f("_npi_bitwise_not", x)


invert = bitwise_not


def left_shift(x1, x2):
    return _f("_npi_left_shift", x1, x2)


def right_shift(x1, x2):
    return _f("_npi_right_shift", x1, x2)


def positive(x):
    return _f("_copy", x)


def modf(x):
    return _direct(jnp.modf, x)


def divmod_(x1, x2):
    return _direct(jnp.divmod, x1, x2)


def signbit(x):
    return _direct(jnp.signbit, x)


def spacing(x):
    # signed, measured away from zero (numpy semantics)
    return _direct(
        lambda v: jnp.nextafter(v, jnp.copysign(jnp.inf, v)) - v, x)


def ptp(a, axis=None, keepdims=False):
    return _direct(jnp.ptp, a, axis=axis, keepdims=keepdims)


# ---------------------------------------------- numpy dispatch protocol
# Reference: python/mxnet/numpy_dispatch_protocol.py — make
# onp.mean(mx_np_array), onp.concatenate([...]) etc. dispatch to this
# module via NEP-18 (__array_function__) and NEP-13 (__array_ufunc__).
_UFUNC_MAP = None
_FUNC_MAP = None


def _build_dispatch_maps():
    global _UFUNC_MAP, _FUNC_MAP
    import sys
    mod = sys.modules[__name__]
    _UFUNC_MAP = {}
    for name in ("add", "subtract", "multiply", "divide", "true_divide",
                 "floor_divide", "power", "mod", "remainder", "sqrt",
                 "square", "absolute", "exp", "log", "log2", "log10",
                 "log1p", "expm1", "sin", "cos", "tan", "arcsin",
                 "arccos", "arctan", "arctan2", "sinh", "cosh", "tanh",
                 "arcsinh", "arccosh", "arctanh", "maximum", "minimum",
                 "negative", "sign", "floor", "ceil", "trunc", "rint",
                 "equal", "not_equal", "less", "less_equal", "greater",
                 "greater_equal", "logical_and", "logical_or",
                 "logical_xor", "isnan", "isinf", "isfinite",
                 "copysign", "ldexp", "fmod", "hypot", "bitwise_and",
                 "bitwise_or", "bitwise_xor"):
        fn = getattr(mod, name, None)
        if fn is not None:
            _UFUNC_MAP[name] = fn
    _FUNC_MAP = {}
    for name in ("mean", "sum", "prod", "max", "min", "argmax", "argmin",
                 "std", "var", "concatenate", "stack", "vstack", "hstack",
                 "dstack", "split", "reshape", "transpose", "squeeze",
                 "expand_dims", "clip", "where", "dot", "tensordot",
                 "einsum", "unique", "nonzero", "sort", "argsort",
                 "cumsum", "around", "broadcast_to", "tile", "repeat",
                 "roll", "flip", "trace", "diff", "ravel", "atleast_1d",
                 "atleast_2d", "atleast_3d", "may_share_memory",
                 "shares_memory", "zeros_like", "ones_like", "meshgrid"):
        fn = getattr(mod, name, None)
        if fn is not None:
            _FUNC_MAP[name] = fn


def _materialize(x):
    """Deep-convert NDArrays (incl. inside lists/tuples/dicts) to host
    numpy so a fallback call cannot re-dispatch back to us."""
    if isinstance(x, NDArray):
        return x.asnumpy()
    if isinstance(x, (list, tuple)):
        return type(x)(_materialize(v) for v in x)
    if isinstance(x, dict):
        return {k: _materialize(v) for k, v in x.items()}
    return x


def _np_array_function(self, func, types, args, kwargs):
    if _FUNC_MAP is None:
        _build_dispatch_maps()
    ours = _FUNC_MAP.get(func.__name__)
    if kwargs.get("out") is not None:
        ours = None  # mapped impls take no out=; use the fallback
    if ours is None:
        # fall back: compute via host numpy on materialized values;
        # an out= mx array receives the result via in-place adoption
        out = kwargs.pop("out", None)
        res = func(*_materialize(list(args)),
                   **_materialize(kwargs))
        if isinstance(out, NDArray):
            out._adopt(jnp.asarray(res, out._data.dtype))
            return out
        if out is not None:
            onp.copyto(out, res)
            return out
        return res
    return ours(*args, **kwargs)


def _np_array_ufunc(self, ufunc, method, *args, **kwargs):
    if _UFUNC_MAP is None:
        _build_dispatch_maps()
    ours = _UFUNC_MAP.get(ufunc.__name__)
    if method == "__call__" and ours is not None \
            and kwargs.get("out") is None:
        kwargs.pop("out", None)
        return ours(*args, **kwargs)
    # fall back to host numpy on materialized values (covers unmapped
    # ufuncs and methods like .reduce/.accumulate/.outer); out= mx
    # arrays receive the result via in-place adoption
    out = kwargs.pop("out", None)
    res = getattr(ufunc, method)(*_materialize(list(args)),
                                 **_materialize(kwargs))
    if out is not None:
        outs = out if isinstance(out, tuple) else (out,)
        ress = res if isinstance(res, tuple) else (res,)
        wrapped = []
        for o, r in zip(outs, ress):
            if isinstance(o, NDArray):
                o._adopt(jnp.asarray(r, o._data.dtype))
                wrapped.append(o)
            else:
                onp.copyto(o, r)
                wrapped.append(o)
        # numpy normalizes out= to a 1-tuple before dispatch; a single
        # out returns the bare array (numpy call semantics)
        return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)
    return res


ndarray.__array_function__ = _np_array_function
ndarray.__array_ufunc__ = _np_array_ufunc
