"""mx.np — NumPy-compatible array namespace.

Reference parity: python/mxnet/numpy/ (multiarray.py 268 defs, linalg,
random) over src/operator/numpy/ (15,457 LoC).  See multiarray.py for
the TPU-native design notes.
"""
from ..ops import numpy_ops  # noqa: F401  (registration side effects)
from .multiarray import *  # noqa: F401,F403
from .multiarray import ndarray, array  # noqa: F401
from . import linalg  # noqa: F401
from . import random  # noqa: F401
