"""Executor: run a Symbol graph as one jitted XLA program.

Reference parity: src/executor/graph_executor.{h,cc} (``GraphExecutor``
bind/simple_bind, Forward/Backward, shared memory pool) — all the graph
passes (memory planning plan_memory.cc, fusion, CSE) collapse into XLA
compilation; backward is ``jax.vjp`` over the compiled forward.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from .. import _rng, autograd
from .. import ndarray as nd
from ..base import MXNetError
from ..context import current_context
from ..ops.registry import get_op

__all__ = ["Executor"]


_BN_OPS = ("BatchNorm", "BatchNorm_v1", "SyncBatchNorm")


def _eval_graph(sym, value_of, key, train, placement=None):
    """Evaluate the DAG: value_of maps variable name -> jax value.

    Returns (outputs list, aux_updates {aux_name: new value}).  During
    training, BatchNorm batch stats fold into the moving aux values
    (reference: the op mutates its aux inputs in place,
    src/operator/nn/batch_norm.cc — no XLA analog, so we thread the
    update out functionally).

    placement: {group_name_or_None: jax device} from bind's group2ctx —
    ops tagged ``__ctx_group__`` (AttrScope) run on their group's
    device with device_put transfers at group boundaries, the
    TPU-native analog of the reference's AssignContext +
    _CrossDeviceCopy (graph_executor.cc:1038, cross_device_copy.cc).
    """
    results = {}  # id(node) -> list of jax values
    aux_updates = {}

    def dev_of(node):
        if placement is None:
            return None
        grp = node.attr_dict.get("__ctx_group__") if node.attr_dict \
            else None
        return placement.get(grp, placement.get(None))

    with _rng.trace_key_scope(key), autograd._Scope(False, train):
        for node in sym._topo():
            dev = dev_of(node)
            if node.op is None:
                v = value_of[node.name]
                if dev is not None:
                    v = jax.device_put(v, dev)
                results[id(node)] = [v]
                continue
            if node.op == "_group":
                continue
            vals = [results[id(inp)][oi] for (inp, oi) in node.inputs]
            if dev is not None:
                vals = [jax.device_put(v, dev) for v in vals]
            opdef = get_op(node.op)
            params = dict(node.attrs)
            if opdef.key_param:
                params[opdef.key_param] = _rng.take_key()
            if opdef.train_param and opdef.train_param not in params:
                params[opdef.train_param] = train
            if (node.op in _BN_OPS and train
                    and not params.get("use_global_stats", False)):
                params["output_mean_var"] = True
                out, batch_mean, batch_var = opdef.fn(*vals, **params)
                m = params.get("momentum", 0.9)
                for slot, stat in ((3, batch_mean), (4, batch_var)):
                    inp, _ = node.inputs[slot]
                    if inp.op is None:
                        old = value_of[inp.name]
                        aux_updates[inp.name] = (
                            m * old + (1.0 - m) * stat.astype(old.dtype))
                results[id(node)] = [out]
                continue
            out = opdef.fn(*vals, **params)
            results[id(node)] = (list(out)
                                 if isinstance(out, (list, tuple))
                                 else [out])
    outs = [results[id(n)][i] for (n, i) in sym._outputs_list()]
    return outs, aux_updates


class Executor:
    """Graph executor (reference GraphExecutor)."""

    def __init__(self, symbol, ctx, args, args_grad, grad_req, aux_states,
                 group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        # manual model parallelism (group2ctx): group name -> device.
        # None maps ungrouped nodes to the default bind context.
        if group2ctx:
            self._placement = {None: self._ctx.jax_device()}
            for g, c in group2ctx.items():
                self._placement[g] = c.jax_device()
        else:
            self._placement = None
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        if isinstance(args, dict):
            self.arg_dict = {
                n: self._as_nd(args[n]) for n in arg_names if n in args}
            missing = [n for n in arg_names if n not in args]
            if missing:
                raise MXNetError(f"missing arguments: {missing}")
        elif args is not None:
            if len(args) != len(arg_names):
                raise MXNetError(
                    f"expected {len(arg_names)} args, got {len(args)}")
            self.arg_dict = {
                n: self._as_nd(a) for n, a in zip(arg_names, args)}
        else:
            raise MXNetError("args required for bind")

        if aux_states is None:
            self.aux_dict = {}
        elif isinstance(aux_states, dict):
            self.aux_dict = {n: self._as_nd(v)
                             for n, v in aux_states.items()}
        else:
            self.aux_dict = {
                n: self._as_nd(a) for n, a in zip(aux_names, aux_states)}
        for n in aux_names:
            if n not in self.aux_dict:
                raise MXNetError(f"missing auxiliary state {n}")

        if self._placement is not None:
            # grouped bind: pre-place every variable on its group's
            # device so per-forward device_puts are no-ops for params
            for node in symbol._topo():
                if node.op is not None:
                    continue
                grp = (node.attr_dict or {}).get("__ctx_group__")
                target = self._placement.get(grp, self._placement[None])
                holder = self.arg_dict.get(node.name)
                if holder is None:
                    holder = self.aux_dict.get(node.name)
                if holder is not None and hasattr(holder._data,
                                                  "devices"):
                    holder._data = jax.device_put(holder._data, target)
        else:
            # co-locate: params loaded from disk are host arrays while
            # data may already live on the chip — a mixed-device bind
            # would fail inside jit.  Unify onto the first argument's
            # device (normally the data input), or onto an
            # explicitly-given bind ctx.
            movable = [v for v in list(self.arg_dict.values())
                       + list(self.aux_dict.values())
                       if hasattr(v._data, "devices")]  # skips tracers
            devs = {next(iter(v._data.devices())) for v in movable}
            if len(devs) > 1 or (ctx is not None and movable):
                if ctx is not None:
                    target = ctx.jax_device()
                else:
                    first = self.arg_dict.get(arg_names[0])
                    target = next(iter(first._data.devices())) \
                        if first is not None and hasattr(first._data,
                                                         "devices") \
                        else next(iter(devs))
                for v in movable:
                    if next(iter(v._data.devices())) != target:
                        v._data = jax.device_put(v._data, target)

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = dict(grad_req)
        if args_grad is None:
            self.grad_dict = {}
        elif isinstance(args_grad, dict):
            self.grad_dict = {n: self._as_nd(v)
                              for n, v in args_grad.items()}
        else:
            self.grad_dict = {
                n: self._as_nd(g)
                for n, g in zip(arg_names, args_grad) if g is not None}

        self._arg_names = arg_names
        self._aux_names = aux_names
        self.outputs = []
        self._vjp_fn = None
        self._fwd_jit = {}
        self.grad_arrays = [self.grad_dict.get(n) for n in arg_names]
        self.arg_arrays = [self.arg_dict[n] for n in arg_names]
        self.aux_arrays = [self.aux_dict[n] for n in aux_names]

    @staticmethod
    def _as_nd(v):
        if isinstance(v, nd.NDArray):
            return v
        return nd.array(onp.asarray(v))

    @classmethod
    def _simple_bind(cls, symbol, ctx, grad_req, shape_kwargs,
                     group2ctx=None):
        """Allocate args/grads from inferred shapes (reference
        simple_bind, graph_executor.cc:803)."""
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape_kwargs)
        if arg_shapes is None or any(s is None for s in arg_shapes):
            raise MXNetError(
                "simple_bind: could not infer all argument shapes from "
                f"{shape_kwargs}")
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        args = {n: nd.zeros(s) for n, s in zip(arg_names, arg_shapes)}
        aux = {n: nd.zeros(s) for n, s in zip(aux_names, aux_shapes)}
        grads = {
            n: nd.zeros(s) for n, s in zip(arg_names, arg_shapes)
            if (grad_req if isinstance(grad_req, str)
                else grad_req.get(n, "write")) != "null"
        }
        return cls(symbol, ctx, args, grads, grad_req, aux,
                   group2ctx=group2ctx)

    # ------------------------------------------------------------- run
    def _fwd_key(self, train):
        shapes = tuple(
            (n, self.arg_dict[n].shape, str(self.arg_dict[n].dtype))
            for n in self._arg_names)
        return (shapes, train)

    def _updated_aux(self, is_train):
        """Aux names whose buffers `_eval_graph` will replace this
        forward — statically readable from the graph (BatchNorm moving
        stats in training mode).  These are the executor's aliasable
        state: the input buffer is dead the moment its update is
        adopted, so the jit path can donate it (the reference's
        static_alloc in-place aux mutation, src/operator/nn/
        batch_norm.cc writes the moving stats into the same blobs)."""
        if not is_train:
            return ()
        names = set()
        for node in self._symbol._topo():
            if node.op not in _BN_OPS:
                continue
            if dict(node.attrs).get("use_global_stats", False):
                continue
            for slot in (3, 4):
                if slot < len(node.inputs):
                    inp, _ = node.inputs[slot]
                    if inp.op is None and inp.name in self.aux_dict:
                        names.add(inp.name)
        return tuple(sorted(names))

    def forward(self, is_train=False, **kwargs):
        from ..config import get_env

        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"unknown argument {k}")
            self.arg_dict[k]._adopt(self._as_nd(v)._data)

        sig = self._fwd_key(is_train)
        entry = self._fwd_jit.get(sig)
        new_entry = entry is None
        if entry is None:
            sym = self._symbol
            don_names = self._updated_aux(is_train)
            rest_names = tuple(n for n in self._aux_names
                               if n not in don_names)
            entry = {"aux_order": None, "don_names": don_names,
                     "rest_names": rest_names}

            placement = self._placement

            def _run(arg_vals, don_vals, rest_vals, key):
                value_of = dict(zip(self._arg_names, arg_vals))
                value_of.update(zip(don_names, don_vals))
                value_of.update(zip(rest_names, rest_vals))
                outs, aux_updates = _eval_graph(sym, value_of, key,
                                                is_train,
                                                placement=placement)
                entry["aux_order"] = tuple(sorted(aux_updates))
                return tuple(outs) + tuple(
                    aux_updates[n] for n in sorted(aux_updates))

            # grouped (group2ctx) executors run per-op with explicit
            # cross-device transfers — jit rejects operands committed
            # to different devices, and XLA compiles one device per
            # program; vjp still traces through the transfers
            entry["fn"] = jax.jit(_run) if placement is None else _run
            # donating twin for the direct-call path: every don_vals
            # leaf has a bit-identical-shaped update output, so XLA
            # aliases each moving-stat buffer instead of allocating a
            # fresh one per step
            entry["fn_d"] = (jax.jit(_run, donate_argnums=(1,))
                             if placement is None and don_names
                             else None)
            self._fwd_jit[sig] = entry

        arg_vals = [self.arg_dict[n]._data for n in self._arg_names]
        don_names = entry["don_names"]
        don_vals = [self.aux_dict[n]._data for n in don_names]
        rest_vals = [self.aux_dict[n]._data
                     for n in entry["rest_names"]]
        key = _rng.take_key()
        n_out = self._symbol.num_outputs

        # trace-platform hint + autotuned variant winners for this
        # program's input signature (the cudnn algo registry consulted
        # at GraphExecutor bind/forward) — active while the jitted
        # graph traces
        from .. import autotune as _at
        from ..ops import pallas_conv as _pc

        plat = _pc.platform_of(arg_vals) or _pc.platform_of(
            don_vals + rest_vals)
        _hint_prev = _pc.set_trace_platform(plat)
        _scope = _at.program_scope(
            tuple(arg_vals[0].shape) if arg_vals else (),
            arg_vals[0].dtype if arg_vals else "none", platform=plat)
        _scope.__enter__()
        if new_entry:
            self._telemetry_trace(sig, is_train, entry, arg_vals,
                                  don_vals, rest_vals, key, _at, plat)
        try:
            if is_train and any(r != "null"
                                for r in self._grad_req.values()):
                fn = entry["fn"]

                def _f(avals):
                    return fn(avals, don_vals, rest_vals, key)

                outs, vjp_fn = jax.vjp(_f, arg_vals)
                self._vjp_fn = vjp_fn
                self._out_avals = [(tuple(map(int, o.shape)), o.dtype)
                                   for o in outs]
                # grouped executors: remember where each output lives so
                # backward can seed cotangents on the matching device
                self._out_devices = [
                    next(iter(o.devices()))
                    if self._placement is not None
                    and hasattr(o, "devices") else None for o in outs]
                self._n_primary = n_out
            else:
                fn_d = entry["fn_d"]
                # donation is only legal when (a) the first
                # (non-donating) trace confirmed every donated buffer
                # really gets a same-shaped update output to alias, and
                # (b) the donated buffers are not aliased into the
                # non-donated operands (a shared NDArray bound as both
                # arg and aux would be consumed while still referenced)
                donate = (fn_d is not None
                          and get_env("MXNET_EXEC_DONATE")
                          and entry["aux_order"] is not None
                          and set(entry["aux_order"]) == set(don_names)
                          and not ({id(v) for v in don_vals}
                                   & {id(v) for v in
                                      arg_vals + rest_vals}))
                if donate:
                    outs = fn_d(arg_vals, don_vals, rest_vals, key)
                else:
                    outs = entry["fn"](arg_vals, don_vals, rest_vals,
                                       key)
                self._vjp_fn = None
        finally:
            _scope.__exit__(None, None, None)
            _pc.set_trace_platform(_hint_prev)
        # fold BatchNorm moving-stat updates back into aux state
        for name, val in zip(entry["aux_order"] or (), outs[n_out:]):
            self.aux_dict[name]._adopt(val)
        self.outputs = [nd.NDArray(o) for o in outs[:n_out]]
        return self.outputs

    def _telemetry_trace(self, sig, is_train, entry, arg_vals, don_vals,
                         rest_vals, key, _at, plat):
        """One compile record + program introspection per new jit
        entry — the Module path's retrace observer.  The RunLog diffs
        this fingerprint against the program's previous one to name
        the retrace cause (shape/dtype/train_mode/autotune_winner).
        No-op when MXNET_RUNLOG is unset; the introspection compile is
        a persistent-cache disk hit when the XLA cache is enabled."""
        from .. import telemetry

        rl = telemetry.current()
        if rl is None:
            return
        shapes, train = sig
        program = f"executor:{getattr(self._symbol, 'name', None) or 'sym'}"
        try:
            probe = tuple(arg_vals[0].shape) if arg_vals else ()
            pdt = arg_vals[0].dtype if arg_vals else "none"
            winners = {}
            if _at.enabled():
                winners = {op: _at.lookup(op, probe, pdt, platform=plat)
                           for op in _at.VARIANT_OPS}
            rl.compile_event(program, telemetry.compile_fingerprint(
                [s for _, s, _ in shapes], [d for _, _, d in shapes],
                train, winners=winners))
            if self._placement is None:
                # memory_analysis/cost_analysis + HLO collective counts
                # of the forward program (grouped executors run eager
                # per-op: nothing to lower)
                telemetry.describe_program(
                    entry["fn"], arg_vals, don_vals, rest_vals, key,
                    program=program)
        except Exception:
            pass  # telemetry must never kill a forward

    def backward(self, out_grads=None, is_train=True):
        """Accumulate into grad arrays per grad_req (reference
        GraphExecutor::Backward)."""
        if self._vjp_fn is None:
            raise MXNetError("backward called before forward(is_train=True)")
        n_primary = self._n_primary
        if out_grads is None:
            cts = [jnp.ones(s, d)
                   for (s, d) in self._out_avals[:n_primary]]
        else:
            if isinstance(out_grads, nd.NDArray):
                out_grads = [out_grads]
            cts = [
                g._data if isinstance(g, nd.NDArray) else jnp.asarray(g)
                for g in out_grads]
        # aux-update extras carry no cotangent
        cts += [jnp.zeros(s, d)
                for (s, d) in self._out_avals[n_primary:]]
        if self._placement is not None:
            # grouped graph: each cotangent must live where its output
            # does, or the first transposed op mixes devices
            cts = [jax.device_put(c, dev) if dev is not None else c
                   for c, dev in zip(cts, self._out_devices)]
        (arg_grads,) = self._vjp_fn(tuple(cts))  # _run returns a tuple
        self._vjp_fn = None
        for n, g in zip(self._arg_names, arg_grads):
            req = self._grad_req.get(n, "write")
            if req == "null" or n not in self.grad_dict:
                continue
            tgt = self.grad_dict[n]
            if req == "add":
                tgt._adopt(tgt._data + g.astype(tgt._data.dtype))
            else:
                tgt._adopt(g.astype(tgt._data.dtype))

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """New executor sharing weights, new data shapes (reference
        GraphExecutor::Reshape — memory sharing is XLA's concern)."""
        new_args = dict(self.arg_dict)
        for n, s in kwargs.items():
            if n in new_args and tuple(new_args[n].shape) != tuple(s):
                new_args[n] = nd.zeros(s)
        return Executor(self._symbol, self._ctx, new_args,
                        dict(self.grad_dict) or None, self._grad_req,
                        dict(self.aux_dict))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for n, v in arg_params.items():
            if n in self.arg_dict:
                self.arg_dict[n]._adopt(self._as_nd(v)._data)
            elif not allow_extra_params:
                raise MXNetError(f"extra param {n}")
        if aux_params:
            for n, v in aux_params.items():
                if n in self.aux_dict:
                    self.aux_dict[n]._adopt(self._as_nd(v)._data)
                elif not allow_extra_params:
                    raise MXNetError(f"extra aux {n}")

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))
