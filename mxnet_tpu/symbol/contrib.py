"""``mx.sym.contrib`` — symbolic higher-order control flow.

Reference: python/mxnet/symbol/contrib.py (foreach/while_loop/cond
building _foreach/_while_loop/_cond graph nodes whose subgraphs
serialize with the Symbol, src/operator/control_flow.cc).  The builders
trace the user's python callable with fresh subgraph input variables;
outer Symbols the body closes over must be variables (weights), which
become extra op inputs shared by node identity with the outer graph.
"""
from __future__ import annotations

import json
import sys

from ..base import MXNetError
from ..ops.registry import _OPS, get_op
from ._op_namespace import _make_sym_func
from .symbol import Symbol, _auto_name, _make_op_symbol, var

_this = sys.modules[__name__]


def _expose_contrib():
    for name in list(_OPS):
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            if short.isidentifier() and not hasattr(_this, short):
                setattr(_this, short, _make_sym_func(name))


from ..ops.control_flow_ops import _states_list as _listify  # noqa: E402


def _subgraph_extras(sub, local_names):
    """Variables the body closed over (weights etc.): the ORIGINAL
    outer var nodes, so the op's inputs unify with the outer graph by
    node identity."""
    extras, seen = [], set()
    for node in sub._topo():
        if node.op is None and node.name not in local_names \
                and node.name not in seen:
            seen.add(node.name)
            extras.append(Symbol(node))
    return extras


def foreach(body, data, init_states, name=None):
    """Symbolic scan (reference symbol/contrib.py:foreach).

    ``body(data_slice, states) -> (outputs, new_states)`` is traced
    once with subgraph variables; returns (outputs, final_states)
    Symbols whose node is a ``_foreach`` op."""
    from .symbol import Group

    name = name or _auto_name("foreach")
    datas, data_single = _listify(data)
    states, states_single = _listify(init_states)
    data_vars = [var(f"{name}_data{i}") for i in range(len(datas))]
    state_vars = [var(f"{name}_state{i}") for i in range(len(states))]
    out, new_states = body(data_vars[0] if data_single else data_vars,
                           state_vars[0] if states_single else state_vars)
    outs, out_single = _listify(out)
    new_states, _ = _listify(new_states)
    if len(new_states) != len(states):
        raise MXNetError("foreach body must return as many states as "
                         "init_states")
    sub = Group(outs + new_states)
    local = {v.name for v in data_vars + state_vars}
    extras = _subgraph_extras(sub, local)
    slot_names = ([v.name for v in data_vars]
                  + [v.name for v in state_vars]
                  + [s.name for s in extras])
    attrs = {
        "subgraph": sub.tojson(),
        "input_names": json.dumps(slot_names),
        "num_data": len(datas),
        "num_states": len(states),
        "num_out_data": len(outs),
    }
    node = _make_op_symbol("_foreach", list(datas) + list(states) + extras,
                           attrs, name)
    out_syms = [node[i] for i in range(len(outs))]
    state_syms = [node[len(outs) + i] for i in range(len(states))]
    return (out_syms[0] if out_single else out_syms,
            state_syms[0] if states_single else state_syms)


def while_loop(cond, func, loop_vars, max_iterations, name=None):
    """Symbolic while (reference symbol/contrib.py:while_loop): outputs
    are stacked over ``max_iterations`` steps (zero-padded after the
    predicate fails), states are the final loop vars."""
    from .symbol import Group

    name = name or _auto_name("while")
    states, states_single = _listify(loop_vars)
    state_vars = [var(f"{name}_state{i}") for i in range(len(states))]
    sv = state_vars[0] if states_single else state_vars
    pred = cond(sv)
    out, new_states = func(sv)
    outs, out_single = _listify(out)
    new_states, _ = _listify(new_states)
    if len(new_states) != len(states):
        raise MXNetError("while_loop func must return as many states "
                         "as loop_vars")
    bsub = Group(outs + new_states)
    csub = Group([pred])
    local = {v.name for v in state_vars}
    extras = _subgraph_extras(Group([pred] + outs + new_states), local)
    slot_names = [v.name for v in state_vars] + [s.name for s in extras]
    attrs = {
        "cond_graph": csub.tojson(),
        "body_graph": bsub.tojson(),
        "input_names": json.dumps(slot_names),
        "num_states": len(states),
        "num_out_data": len(outs),
        "max_iterations": int(max_iterations),
    }
    node = _make_op_symbol("_while_loop", list(states) + extras, attrs,
                           name)
    out_syms = [node[i] for i in range(len(outs))]
    state_syms = [node[len(outs) + i] for i in range(len(states))]
    return (out_syms[0] if out_single else out_syms,
            state_syms[0] if states_single else state_syms)


def cond(pred, then_func, else_func, inputs=None, name=None):
    """Symbolic branch (reference symbol/contrib.py:cond).

    ``pred``/``then_func``/``else_func`` are callables taking the
    ``inputs`` Symbols (a list; [] allowed for closures over outer
    variables)."""
    from .symbol import Group

    name = name or _auto_name("cond")
    ins, single = _listify(inputs if inputs is not None else [])
    in_vars = [var(f"{name}_in{i}") for i in range(len(ins))]
    iv = in_vars[0] if single and ins else in_vars
    p = pred(iv) if ins else pred()
    t = then_func(iv) if ins else then_func()
    e = else_func(iv) if ins else else_func()
    t_list, t_single = _listify(t)
    e_list, _ = _listify(e)
    if len(t_list) != len(e_list):
        raise MXNetError("then and else branches must return the same "
                         "number of outputs")
    local = {v.name for v in in_vars}
    union = Group([p] + t_list + e_list)
    extras = _subgraph_extras(union, local)
    slot_names = [v.name for v in in_vars] + [s.name for s in extras]
    attrs = {
        "cond_graph": Group([p]).tojson(),
        "then_graph": Group(t_list).tojson(),
        "else_graph": Group(e_list).tojson(),
        "input_names": json.dumps(slot_names),
        "num_outputs": len(t_list),
    }
    node = _make_op_symbol("_cond", list(ins) + extras, attrs, name)
    out_syms = [node[i] for i in range(len(t_list))]
    return out_syms[0] if t_single else out_syms


_expose_contrib()
