"""Symbol: the declarative graph API.

Reference parity: python/mxnet/symbol/symbol.py (``Symbol`` composition
:55, ``infer_shape`` :1045, ``bind``/``simple_bind`` :1504/:1806,
``tojson`` :1369) and the nnvm graph JSON schema, including the legacy
"param"-style upgrade path (src/nnvm/legacy_json_util.cc).

TPU-native redesign: a Symbol is a lightweight DAG of (op, inputs,
attrs); ``bind`` translates the DAG into ONE jitted XLA program (the
whole GraphExecutor pass pipeline — shape inference, memory planning,
fusion, CSE — collapses into XLA compilation, SURVEY.md §7).
"""
from __future__ import annotations

import json

import numpy as onp

from .. import _rng, autograd
from ..base import MXNetError
from ..ops.registry import get_op

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "AttrScope"]


class AttrScope:
    """Attribute scope applied to every symbol created inside it
    (reference python/mxnet/attribute.py AttrScope): the manual
    model-parallel API tags ops with a context group,

        with mx.AttrScope(ctx_group="dev1"):
            h = mx.sym.FullyConnected(x, num_hidden=128)

    and ``bind(group2ctx={"dev1": mx.gpu(0)})`` maps each group to a
    device (see symbol/executor.py).  Keys are stored decorated as
    ``__key__`` (the reference's convention for framework attrs)."""

    import threading as _threading

    _local = _threading.local()

    def __init__(self, **attrs):
        self._attrs = {f"__{k}__": str(v) for k, v in attrs.items()}
        self._prev = None

    @classmethod
    def current(cls):
        return getattr(cls._local, "attrs", {})

    def __enter__(self):
        self._prev = dict(self.current())
        merged = dict(self._prev)
        merged.update(self._attrs)
        AttrScope._local.attrs = merged
        return self

    def __exit__(self, *exc):
        AttrScope._local.attrs = self._prev
        return False

_UNNAMED_COUNT = {}


def _auto_name(hint):
    n = _UNNAMED_COUNT.get(hint, 0)
    _UNNAMED_COUNT[hint] = n + 1
    return f"{hint}{n}"


# op input-name metadata: which op inputs are auxiliary states
# (reference: mutable inputs declared by the op, e.g. BatchNorm moving
# stats — nnvm FMutateInputs)
_AUX_INPUTS = {
    "BatchNorm": (3, 4),
    "BatchNorm_v1": (3, 4),
    "SyncBatchNorm": (3, 4),
}

class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "attr_dict")

    def __init__(self, op, name, attrs, inputs, num_outputs=1,
                 attr_dict=None):
        self.op = op  # None for variables, else registry op name
        self.name = name
        self.attrs = attrs  # op hyper-params {str: value}
        self.inputs = inputs  # list of (node, out_idx)
        self.num_outputs = num_outputs
        self.attr_dict = attr_dict or {}  # user attrs (lr_mult etc.)


class Symbol:
    """Handle to one or more outputs of a graph node."""

    def __init__(self, node, out_index=None):
        self._node = node
        self._out = out_index  # None = all outputs

    # ----------------------------------------------------------- info
    @property
    def name(self):
        if self._node.num_outputs > 1 and self._out is not None:
            return f"{self._node.name}_output{self._out}"
        return self._node.name

    def attr(self, key):
        return self._node.attr_dict.get(key)

    def attr_dict(self):
        out = {}
        for node in self._topo():
            if node.attr_dict:
                out[node.name] = dict(node.attr_dict)
        return out

    def list_attr(self):
        return dict(self._node.attr_dict)

    def _outputs_list(self):
        if self._out is not None:
            return [(self._node, self._out)]
        if self._node.op == "_group":
            outs = []
            for (n, i) in self._node.inputs:
                outs.append((n, i))
            return outs
        return [(self._node, i) for i in range(self._node.num_outputs)]

    @property
    def num_outputs(self):
        return len(self._outputs_list())

    def __getitem__(self, index):
        outs = self._outputs_list()
        if isinstance(index, str):
            names = [self._out_name(n, i) for (n, i) in outs]
            if index not in names:
                raise MXNetError(f"no output named {index}")
            index = names.index(index)
        node, oidx = outs[index]
        return Symbol(node, oidx)

    @staticmethod
    def _out_name(node, i):
        """Reference convention: op outputs are '<name>_output' (indexed
        when the op has several); variables keep their own name."""
        if node.op is None:
            return node.name
        if node.num_outputs > 1:
            return f"{node.name}_output{i}"
        return f"{node.name}_output"

    def __iter__(self):
        return (self[i] for i in range(self.num_outputs))

    def __len__(self):
        return self.num_outputs

    def _topo(self):
        """Topological order of reachable nodes."""
        order, seen = [], set()
        stack = [(self._node, False)]
        while stack:
            node, done = stack.pop()
            if done:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for (inp, _) in reversed(node.inputs):
                if id(inp) not in seen:
                    stack.append((inp, False))
        return order

    def list_arguments(self):
        return [n.name for n in self._topo()
                if n.op is None and not n.attr_dict.get("__aux__")]

    def list_auxiliary_states(self):
        return [n.name for n in self._topo()
                if n.op is None and n.attr_dict.get("__aux__")]

    def list_outputs(self):
        return [self._out_name(n, i) for (n, i) in self._outputs_list()]

    def list_inputs(self):
        return [n.name for n in self._topo() if n.op is None]

    def get_internals(self):
        nodes = [n for n in self._topo()]
        outs = []
        for n in nodes:
            for i in range(n.num_outputs):
                outs.append((n, i))
        g = _Node("_group", _auto_name("group"), {},
                  outs, num_outputs=len(outs))
        return Symbol(g)

    def get_children(self):
        if not self._node.inputs:
            return None
        g = _Node("_group", _auto_name("group"), {},
                  list(self._node.inputs),
                  num_outputs=len(self._node.inputs))
        return Symbol(g)

    # ------------------------------------------------------- arithmetic
    def _binary(self, other, opname, scalar_op, reverse=False):
        # reverse variants are dedicated ops (_rminus_scalar, ...)
        if isinstance(other, Symbol):
            return _make_op_symbol(opname, [self, other], {}, None)
        return _make_op_symbol(scalar_op, [self],
                               {"scalar": float(other)}, None)

    def __add__(self, other):
        return self._binary(other, "elemwise_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, "elemwise_sub", "_rminus_scalar",
                            reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elemwise_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binary(other, "elemwise_div", "_rdiv_scalar",
                            reverse=True)

    def __pow__(self, other):
        return self._binary(other, "_power", "_power_scalar")

    def __neg__(self):
        return self.__mul__(-1.0)

    # comparisons (reference symbol.py __gt__/...: 1.0/0.0 outputs)
    def __gt__(self, other):
        return self._binary(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "broadcast_greater_equal",
                            "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binary(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "broadcast_lesser_equal",
                            "_lesser_equal_scalar")

    def __eq__(self, other):
        if not isinstance(other, (Symbol, int, float)):
            return NotImplemented
        return self._binary(other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        if not isinstance(other, (Symbol, int, float)):
            return NotImplemented
        return self._binary(other, "broadcast_not_equal",
                            "_not_equal_scalar")

    def __hash__(self):
        return id(self._node) ^ hash(self._out)

    def __bool__(self):
        # __eq__ builds a graph node, so truthiness of a comparison is
        # meaningless — fail loudly (reference NotImplementedForSymbol)
        raise MXNetError(
            "a Symbol has no boolean value; use `is`/`is not` for "
            "identity, or execute the graph for elementwise comparison")

    # ------------------------------------------------------- evaluation
    def _eval(self, value_of):
        """Evaluate outputs given a dict node->list[jax value] resolver."""
        raise NotImplementedError  # executor drives evaluation

    def infer_shape(self, *args, **kwargs):
        """(arg_shapes, out_shapes, aux_shapes) via abstract eval."""
        import jax

        known = dict(kwargs)
        if args:
            for name, s in zip(self.list_arguments(), args):
                if s is not None:
                    known[name] = s
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        shapes = _infer_all_shapes(self, known)
        if shapes is None:
            return None, None, None
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        out_shapes = [shapes[("__out__", i)]
                      for i in range(self.num_outputs)]
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self.infer_shape(*args, **kwargs)
        except MXNetError:
            return None, None, None

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dt = onp.float32
        return ([dt] * len(arg_names),
                [dt] * self.num_outputs,
                [dt] * len(self.list_auxiliary_states()))

    # -------------------------------------------------------------- io
    def tojson(self):
        """Serialize in the reference nnvm JSON schema
        (symbol.py:1369)."""
        # synthetic _group containers are not real graph nodes — heads
        # reference their members directly
        nodes_list = [n for n in self._topo() if n.op != "_group"]
        node_id = {id(n): i for i, n in enumerate(nodes_list)}
        nodes = []
        arg_nodes = []
        for i, n in enumerate(nodes_list):
            entry = {
                "op": "null" if n.op is None else n.op,
                "name": n.name,
                "inputs": [[node_id[id(inp)], oi, 0]
                           for (inp, oi) in n.inputs],
            }
            attrs = {k: _attr_str(v) for k, v in n.attrs.items()}
            if attrs:
                entry["attrs"] = attrs
            user_attrs = {k: str(v) for k, v in n.attr_dict.items()
                          if not k.startswith("__")
                          or k in ("__shape__", "__dtype__", "__init__")}
            if user_attrs:
                entry["attr"] = user_attrs
            if n.op is None:
                arg_nodes.append(i)
            nodes.append(entry)
        heads = [[node_id[id(n)], i, 0] for (n, i) in self._outputs_list()]
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10600]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------- executors
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from .executor import Executor

        return Executor._simple_bind(self, ctx, grad_req, kwargs,
                                     group2ctx=group2ctx)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def __repr__(self):
        return f"<Symbol {self.name}>"

    # gluon SymbolBlock / functional composition support
    def __call__(self, *args, **kwargs):
        s = self._clone()
        s._compose(*args, **kwargs)
        return s

    def __copy__(self):
        return self._clone()

    def _clone(self):
        """Deep-copy the reachable graph so composition never mutates
        the original (reference Symbol composition copies the graph)."""
        mapping = {}
        for node in self._topo():  # child-first order
            mapping[id(node)] = _Node(
                node.op, node.name, dict(node.attrs),
                [(mapping[id(inp)], oi) for (inp, oi) in node.inputs],
                num_outputs=node.num_outputs,
                attr_dict=dict(node.attr_dict))
        return Symbol(mapping[id(self._node)], self._out)

    def _compose(self, *args, **kwargs):
        """Replace variable inputs with the given symbols (reference
        Symbol composition).  Positional args map to distinct variables
        in list_inputs() order; a variable used at several sites gets the
        same replacement everywhere."""
        name = kwargs.pop("name", None)
        if name is not None:
            self._node.name = name
        if args and kwargs:
            raise MXNetError(
                "compose only accepts input Symbols either as positional "
                "or keyword arguments, not both")
        repl_of = {}  # variable node name -> replacement (node, oidx)
        for k, v in kwargs.items():
            repl_of[k] = (v._node, v._out if v._out is not None else 0)
        if args:
            pos = list(args)
            for node in self._topo():
                if node.op is None and node.name not in repl_of and pos:
                    v = pos.pop(0)
                    repl_of[node.name] = (
                        v._node, v._out if v._out is not None else 0)
        for node in self._topo():
            node.inputs = [
                repl_of[inp.name] if (inp.op is None
                                      and inp.name in repl_of)
                else (inp, oi)
                for (inp, oi) in node.inputs
            ]


def _attr_str(v):
    if isinstance(v, bool):
        return "True" if v else "False"
    if isinstance(v, (list, tuple)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable (reference symbol.py var())."""
    attr_dict = dict(attr or {})
    if shape is not None:
        attr_dict["__shape__"] = tuple(shape)
    if lr_mult is not None:
        attr_dict["lr_mult"] = lr_mult
    if wd_mult is not None:
        attr_dict["wd_mult"] = wd_mult
    if dtype is not None:
        attr_dict["__dtype__"] = str(dtype)
    if init is not None:
        attr_dict["__init__"] = init if isinstance(init, str) else (
            init.dumps())
    scoped = AttrScope.current()
    if scoped:
        attr_dict = {**scoped, **attr_dict}
    node = _Node(None, name, {}, [], attr_dict=attr_dict)
    return Symbol(node)


var = Variable


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs_list())
    node = _Node("_group", _auto_name("group"), {}, outs,
                 num_outputs=len(outs))
    return Symbol(node)


def _make_op_symbol(opname, input_syms, attrs, name, num_outputs=None):
    """Create an op node (used by the generated sym.* functions)."""
    opdef = get_op(opname)
    if name is None:
        name = _auto_name(opname.lower().strip("_"))
    if num_outputs is None:
        num_outputs = opdef.out_count(attrs)
    input_syms = list(input_syms)
    slot_names = _op_input_names(opname, attrs)
    if slot_names is not None and len(input_syms) < len(slot_names):
        aux_slots = _AUX_INPUTS.get(opname, ())
        for slot in range(len(input_syms), len(slot_names)):
            v = Variable(f"{name}_{slot_names[slot]}")
            if slot in aux_slots:
                v._node.attr_dict["__aux__"] = True
            input_syms.append(v)
    inputs = []
    for s in input_syms:
        inputs.append((s._node, s._out if s._out is not None else 0))
    # explicitly-passed variables feeding aux slots (BatchNorm moving
    # stats) are aux states too, same as the auto-created ones above
    for slot in _AUX_INPUTS.get(opname, ()):
        if slot < len(inputs) and inputs[slot][0].op is None:
            inputs[slot][0].attr_dict["__aux__"] = True
    node = _Node(opname, name, attrs, inputs, num_outputs=num_outputs)
    scoped = AttrScope.current()
    if scoped:
        node.attr_dict.update(scoped)
    return Symbol(node)


def _infer_all_shapes(sym, known_shapes):
    """Abstract-eval the graph to resolve every variable/out shape (the
    reference InferShape pass, infer_graph_attr_pass.cc)."""
    from . import _shape_infer

    arg_names = sym.list_arguments() + sym.list_auxiliary_states()
    shapes = {}
    for n in arg_names:
        if n in known_shapes:
            shapes[n] = tuple(known_shapes[n])
    for node in sym._topo():
        if node.op is None and "__shape__" in node.attr_dict:
            shapes.setdefault(node.name, node.attr_dict["__shape__"])
    return _shape_infer.infer(sym, shapes)


# Which named inputs an op consumes, for auto-creating missing parameter
# variables (reference: sym.FullyConnected(data, num_hidden=N, name="fc")
# creates fc_weight / fc_bias; nnvm FListInputNames)
def _op_input_names(opname, attrs):
    if opname in ("FullyConnected", "Convolution", "Convolution_v1"):
        names = ["data", "weight"]
        if not attrs.get("no_bias", False):
            names.append("bias")
        return names
    if opname == "Deconvolution":
        names = ["data", "weight"]
        if not attrs.get("no_bias", True):
            names.append("bias")
        return names
    if opname in ("BatchNorm", "BatchNorm_v1", "SyncBatchNorm"):
        return ["data", "gamma", "beta", "moving_mean", "moving_var"]
    if opname in ("LayerNorm", "InstanceNorm", "GroupNorm"):
        return ["data", "gamma", "beta"]
    if opname == "Embedding":
        return ["data", "weight"]
    if opname == "LeakyReLU" and attrs.get("act_type") == "prelu":
        return ["data", "gamma"]
    if opname in ("SoftmaxOutput", "LinearRegressionOutput",
                  "LogisticRegressionOutput", "MAERegressionOutput",
                  "SVMOutput"):
        return ["data", "label"]
    if opname == "RNN":
        names = ["data", "parameters", "state"]
        if attrs.get("mode", "lstm") == "lstm":
            names.append("state_cell")
        return names
    return None  # unknown: no auto-creation


def load_json(json_str):
    """Parse reference JSON (modern attrs or legacy param schema —
    legacy_json_util.cc upgrade path)."""
    data = json.loads(json_str)
    nodes_json = data["nodes"]
    built = []
    for nj in nodes_json:
        op = nj["op"]
        attrs_raw = nj.get("attrs", nj.get("param", {})) or {}
        if isinstance(attrs_raw, list):
            attrs_raw = dict(attrs_raw)
        user_attr = nj.get("attr", {}) or {}
        inputs = [(built[i], oi) for i, oi, *_ in nj.get("inputs", [])]
        if op == "null":
            ad = dict(user_attr)
            if isinstance(ad.get("__shape__"), str):
                import ast

                ad["__shape__"] = tuple(
                    ast.literal_eval(ad["__shape__"]))
            node = _Node(None, nj["name"], {}, [], attr_dict=ad)
        else:
            opdef = get_op(op)  # raises for unknown op
            attrs = _parse_attrs(op, attrs_raw)
            node = _Node(op, nj["name"], attrs, inputs,
                         num_outputs=opdef.out_count(attrs),
                         attr_dict=dict(user_attr))
            # legacy (v0.8 "param"-schema) graphs omit aux-state inputs
            # (BatchNorm moving stats); append fresh variables for them
            slot_names = _op_input_names(op, attrs)
            if slot_names is not None and len(inputs) < len(slot_names):
                aux_slots = _AUX_INPUTS.get(op, ())
                for slot in range(len(inputs), len(slot_names)):
                    v = _Node(None, f"{nj['name']}_{slot_names[slot]}",
                              {}, [])
                    if slot in aux_slots:
                        v.attr_dict["__aux__"] = True
                    node.inputs.append((v, 0))
        built.append(node)
    heads = data.get("heads", [[len(built) - 1, 0, 0]])
    if len(heads) == 1:
        h = heads[0]
        sym = Symbol(built[h[0]], h[1] if built[h[0]].num_outputs > 1
                     else None)
        if built[h[0]].num_outputs == 1:
            sym = Symbol(built[h[0]], None)
        _mark_aux(sym)
        return sym
    outs = [(built[h[0]], h[1]) for h in heads]
    g = _Node("_group", _auto_name("group"), {}, outs,
              num_outputs=len(outs))
    sym = Symbol(g)
    _mark_aux(sym)
    return sym


def _mark_aux(sym):
    """Tag variables feeding aux input slots (BatchNorm moving stats)."""
    for node in sym._topo():
        if node.op in _AUX_INPUTS:
            for slot in _AUX_INPUTS[node.op]:
                idx = slot  # input slot index incl. data at 0
                if idx < len(node.inputs):
                    inp, _ = node.inputs[idx]
                    if inp.op is None:
                        inp.attr_dict["__aux__"] = True


def _parse_attrs(opname, raw):
    """Parse string attr values to python (reference dmlc::Parameter
    string-kwarg parsing)."""
    import ast

    opdef = get_op(opname)
    valid = set(opdef.param_names)
    out = {}
    for k, v in raw.items():
        if k not in valid:
            continue  # ignore attrs the TPU op doesn't take (cudnn_*, ...)
        if not isinstance(v, str):
            out[k] = v
            continue
        s = v.strip()
        try:
            out[k] = ast.literal_eval(s)
            continue
        except (ValueError, SyntaxError):
            pass
        if s in ("True", "true"):
            out[k] = True
        elif s in ("False", "false"):
            out[k] = False
        else:
            out[k] = s
    return out


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


