"""``mx.sym`` — symbolic front-end (reference: python/mxnet/symbol/)."""
from .symbol import *  # noqa: F401,F403
from .symbol import (  # noqa: F401
    AttrScope, Symbol, Variable, var, Group, load, load_json)
from . import _op_namespace  # noqa: F401  (populates sym.<Op> functions)
from ._op_namespace import *  # noqa: F401,F403
from . import contrib  # noqa: E402,F401  (sym.contrib.foreach/while_loop/cond)
