"""Shape inference over a Symbol DAG.

Reference parity: src/executor/infer_graph_attr_pass.cc (InferShape pass)
— one forward topological sweep; unshaped parameter variables feeding a
parameterized op are deduced from the op's convention (the reference
encodes the same rules in each op's FInferShape).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..ops.registry import get_op


def _tup(v, n, default=1):
    if v is None:
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _deduce_param_shapes(op, attrs, input_shapes, slot_names):
    """Given the data input shape (slot 0), return {slot: shape} for
    parameter slots that are still unknown."""
    data = input_shapes[0]
    if data is None:
        return {}
    out = {}
    if op == "FullyConnected":
        flatten = attrs.get("flatten", True)
        num_hidden = attrs["num_hidden"]
        in_units = (int(onp.prod(data[1:])) if flatten else data[-1])
        out[1] = (num_hidden, in_units)
        out[2] = (num_hidden,)
    elif op in ("Convolution", "Convolution_v1"):
        kernel = _tup(attrs["kernel"], 0)
        num_filter = attrs["num_filter"]
        num_group = attrs.get("num_group", 1)
        out[1] = (num_filter, data[1] // num_group) + tuple(kernel)
        out[2] = (num_filter,)
    elif op == "Deconvolution":
        kernel = _tup(attrs["kernel"], 0)
        num_filter = attrs["num_filter"]
        num_group = attrs.get("num_group", 1)
        out[1] = (data[1], num_filter // num_group) + tuple(kernel)
        out[2] = (num_filter,)
    elif op in ("BatchNorm", "BatchNorm_v1", "SyncBatchNorm"):
        axis = attrs.get("axis", 1)
        c = data[axis % len(data)]
        for slot in (1, 2, 3, 4):
            out[slot] = (c,)
    elif op == "InstanceNorm":
        out[1] = (data[1],)
        out[2] = (data[1],)
    elif op == "LayerNorm":
        axis = attrs.get("axis", -1)
        c = data[axis % len(data)]
        out[1] = (c,)
        out[2] = (c,)
    elif op == "GroupNorm":
        ng = attrs.get("num_groups", 1)
        out[1] = (ng,)
        out[2] = (ng,)
    elif op == "Embedding":
        out[1] = (attrs["input_dim"], attrs["output_dim"])
    elif op == "LeakyReLU" and attrs.get("act_type") == "prelu":
        out[1] = (data[1],)
    elif op in ("SoftmaxOutput", "Softmax"):
        # sparse class labels: one per leading-dims element
        out[1] = tuple(data[:-1]) if not attrs.get("multi_output") else (
            (data[0],) + tuple(data[2:]))
    elif op in ("LinearRegressionOutput", "LogisticRegressionOutput",
                "MAERegressionOutput", "SVMOutput"):
        out[1] = tuple(data)
    elif op == "RNN":
        from ..ops.rnn import rnn_param_size

        mode = attrs.get("mode", "lstm")
        nl = attrs.get("num_layers", 1)
        h = attrs["state_size"]
        bi = attrs.get("bidirectional", False)
        proj = attrs.get("projection_size")
        r = proj if proj else h
        d = 2 if bi else 1
        t, n, input_size = data
        out[1] = (rnn_param_size(mode, nl, input_size, h, bi, proj),)
        out[2] = (nl * d, n, r)
        out[3] = (nl * d, n, h)
    return out


def infer(sym, shapes):
    """Return {var_name: shape, ("__out__", i): shape} or raise."""
    from .symbol import Symbol

    node_out_shapes = {}  # id(node) -> [shape per output]
    dtype = jnp.float32

    for node in sym._topo():
        if node.op is None:
            s = shapes.get(node.name)
            node_out_shapes[id(node)] = [s]
            continue
        if node.op == "_group":
            continue
        in_shapes = [node_out_shapes[id(inp)][oi]
                     for (inp, oi) in node.inputs]
        # deduce unknown parameter-variable shapes
        if any(s is None for s in in_shapes):
            deduced = _deduce_param_shapes(node.op, node.attrs, in_shapes,
                                           None)
            for slot, shape in deduced.items():
                if slot < len(node.inputs) and in_shapes[slot] is None:
                    inp, oi = node.inputs[slot]
                    if inp.op is None:
                        shapes[inp.name] = shape
                        node_out_shapes[id(inp)] = [shape]
                        in_shapes[slot] = shape
            # elementwise fallback: same-shape as first known input
            if any(s is None for s in in_shapes):
                known = next((s for s in in_shapes if s is not None), None)
                opdef = get_op(node.op)
                if known is not None and node.op.startswith(
                        ("elemwise_", "_plus", "_minus", "_mul", "_div")):
                    for i, s in enumerate(in_shapes):
                        if s is None:
                            inp, oi = node.inputs[i]
                            if inp.op is None:
                                shapes[inp.name] = known
                                node_out_shapes[id(inp)] = [known]
                                in_shapes[i] = known
        if any(s is None for s in in_shapes):
            missing = [n.name for (n, _), s in zip(node.inputs, in_shapes)
                       if s is None]
            raise MXNetError(
                f"InferShape: cannot deduce shapes of {missing} feeding "
                f"op {node.op}({node.name})")
        # abstract-eval this node
        opdef = get_op(node.op)
        params = dict(node.attrs)
        if opdef.key_param:
            params[opdef.key_param] = jax.random.key(0)
        if opdef.train_param and opdef.train_param not in params:
            params[opdef.train_param] = False
        structs = [jax.ShapeDtypeStruct(s, dtype) for s in in_shapes]
        try:
            out = jax.eval_shape(
                lambda *xs: opdef.fn(*xs, **params), *structs)
        except Exception as e:
            raise MXNetError(
                f"InferShape failed at op {node.op}({node.name}) with "
                f"input shapes {in_shapes}: {e}") from e
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        node_out_shapes[id(node)] = [tuple(o.shape) for o in outs]

    result = dict(shapes)
    for i, (n, oi) in enumerate(sym._outputs_list()):
        result[("__out__", i)] = node_out_shapes[id(n)][oi]
    return result
