"""Generate ``mx.sym.<Op>`` functions from the registry.

Reference parity: python/mxnet/symbol/register.py generates Python source
per registered op at import time; here we generate closures (same pattern
as mxnet_tpu/ndarray/__init__.py).
"""
from __future__ import annotations

import inspect

from ..ops.registry import get_op, list_ops
from .symbol import Symbol, _make_op_symbol

__all__ = []


def _tensor_names(opdef):
    sig = inspect.signature(opdef.fn)
    names, variadic = [], False
    for p in sig.parameters.values():
        if p.kind is inspect.Parameter.POSITIONAL_OR_KEYWORD:
            names.append(p.name)
        elif p.kind is inspect.Parameter.VAR_POSITIONAL:
            variadic = True
    return names, variadic


def _make_sym_func(opname):
    opdef = get_op(opname)
    tnames, variadic = _tensor_names(opdef)
    kw_names = set(opdef.param_names)

    def sym_func(*args, **kwargs):
        name = kwargs.pop("name", None)
        attrs = {}
        inputs = list(args)
        # split kwargs into tensor inputs (by name) and hyper-params
        named_inputs = {}
        for k, v in list(kwargs.items()):
            if isinstance(v, Symbol):
                named_inputs[k] = v
                kwargs.pop(k)
        for k, v in kwargs.items():
            if k in kw_names or True:
                attrs[k] = v
        if named_inputs and not variadic:
            # order named tensor inputs per signature
            merged = list(inputs)
            for tn in tnames[len(inputs):]:
                if tn in named_inputs:
                    merged.append(named_inputs.pop(tn))
            # common alias: 'data' as first input
            if named_inputs:
                for k in list(named_inputs):
                    merged.append(named_inputs.pop(k))
            inputs = merged
        elif named_inputs:
            inputs.extend(named_inputs.values())
        if not all(isinstance(s, Symbol) for s in inputs):
            raise TypeError(
                f"sym.{opname} inputs must be Symbols, got "
                f"{[type(s).__name__ for s in inputs]}")
        attrs = {k: v for k, v in attrs.items() if v is not None}
        return _make_op_symbol(opname, inputs, attrs, name)

    sym_func.__name__ = opname
    sym_func.__doc__ = opdef.doc
    return sym_func


# NOTE: an op is literally named "_mod" — assign via globals() so no
# module-alias variable can be shadowed by a generated function
def _expose_new_ops():
    """(Re)generate sym.<Op> functions — idempotent; called again by
    mx.library.load for plugin ops.  Also patches the parent package
    (mxnet_tpu.symbol), whose star-import copy of this namespace was
    frozen at import time."""
    import sys

    pkg = sys.modules.get("mxnet_tpu.symbol")
    for _name in list_ops():
        if _name not in globals():
            fn = _make_sym_func(_name)
            globals()[_name] = fn
            if pkg is not None and not hasattr(pkg, _name):
                setattr(pkg, _name, fn)


for _name in list_ops():
    _f = _make_sym_func(_name)
    globals()[_name] = _f
    __all__.append(_name)
