"""Native (C++) data-plane extension loader.

Builds ``src/recordio_native.cc`` into a shared library on first use
(g++ + libjpeg, both baked into the image) and binds it via ctypes —
the TPU-native stand-in for the reference's C++ io/ tree
(src/io/iter_image_recordio_2.cc + image_aug_default.cc).  All heavy
loops run with the GIL released (ctypes drops it for the call).

``get_lib()`` returns None when the toolchain/libjpeg are unavailable;
callers fall back to the pure-Python (PIL/cv2) path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as onp

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src", "recordio_native.cc")
_OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "_build")


def build_native(src, out_name, ldflags=(), opt="-O3"):
    """Build ``src`` into ``_build/<out_name>`` and return the path.

    ATOMIC against concurrent builders (launch.py starts N worker
    processes that may all hit a cold cache simultaneously): compile to
    a per-pid temp file, then os.replace onto the final name — a
    concurrent reader either sees the old complete file or the new
    complete file, never a half-written ELF."""
    os.makedirs(_OUT_DIR, exist_ok=True)
    out = os.path.join(_OUT_DIR, out_name)
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = ["g++", opt, "-shared", "-fPIC", "-std=c++17", src,
           "-o", tmp] + list(ldflags)
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out


def _build():
    return build_native(_SRC, "librecordio_native.so",
                        ldflags=("-ljpeg", "-lpthread"))


def get_lib():
    """The loaded native library, building it on first call; None if
    the build fails (pure-Python fallback paths take over)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            path = _build()
            lib = ctypes.CDLL(path)
        except Exception:
            _lib = None
            return None
        i64 = ctypes.c_int64
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.rec_parse.restype = i64
        lib.rec_parse.argtypes = [u8p, i64, i64p, i64p, u32p, i64]
        lib.decode_augment_batch.restype = i64
        lib.decode_augment_batch.argtypes = [
            u8p, i64p, i64p, i64, f32p, i64, i64, f32p, f32p, f32p,
            f32p, u8p, ctypes.c_int, ctypes.c_int]
        lib.rec_jpeg_size.restype = ctypes.c_int
        lib.rec_jpeg_size.argtypes = [u8p, i64, ctypes.POINTER(
            ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.rec_jpeg_decode.restype = ctypes.c_int
        lib.rec_jpeg_decode.argtypes = [u8p, i64, u8p, ctypes.c_int,
                                        ctypes.c_int]
        _lib = lib
        return _lib


def _ptr(a, typ):
    return a.ctypes.data_as(ctypes.POINTER(typ))


def parse_records(buf, return_offsets=False):
    """Split a raw .rec byte buffer into payload memoryviews using the
    native parser (dmlc framing incl. continuation flags).

    ``return_offsets=True`` also returns each LOGICAL record's
    frame-start byte offset (``(records, offsets)``) — the parser
    already computes them, and the data plane's quarantine manifest
    promises seekable offsets."""
    lib = get_lib()
    arr = onp.frombuffer(buf, dtype=onp.uint8)
    max_records = max(len(arr) // 8, 1)
    offsets = onp.empty(max_records, onp.int64)
    sizes = onp.empty(max_records, onp.int64)
    lflags = onp.empty(max_records, onp.uint32)
    n = lib.rec_parse(_ptr(arr, ctypes.c_uint8), len(arr),
                      _ptr(offsets, ctypes.c_int64),
                      _ptr(sizes, ctypes.c_int64),
                      _ptr(lflags, ctypes.c_uint32), max_records)
    if n < 0:
        raise IOError("invalid recordio framing")
    if n > 0 and int(offsets[n - 1] + sizes[n - 1]) > len(arr):
        raise IOError(
            "truncated recordio buffer: last record extends past EOF")
    records = []
    rec_offsets = []  # frame start of each logical record
    i = 0
    mv = memoryview(buf)
    magic = onp.uint32(0xCED7230A).tobytes()
    while i < n:
        rec_offsets.append(int(offsets[i]) - 8)  # payload - header
        if lflags[i] == 0:  # whole record in one part
            records.append(mv[offsets[i]:offsets[i] + sizes[i]])
            i += 1
        else:
            # multi-part record: the writer split the payload wherever
            # it contained the magic bytes, stripping them — rejoin
            # with the magic as separator (recordio.py MXRecordIO.read)
            parts = [bytes(mv[offsets[i]:offsets[i] + sizes[i]])]
            i += 1
            while i < n and lflags[i] in (2, 3):
                parts.append(bytes(mv[offsets[i]:offsets[i] + sizes[i]]))
                end = lflags[i] == 3
                i += 1
                if end:
                    break
            records.append(memoryview(magic.join(parts)))
    if return_offsets:
        return records, rec_offsets
    return records


def decode_augment_batch(jpeg_list, out_h, out_w, mean=None, std=None,
                         crop_x=None, crop_y=None, mirror=None,
                         resize_short=-1, num_threads=0):
    """Threaded decode+augment of a list of JPEG byte strings into an
    NCHW float32 batch.  Returns (batch, n_failed)."""
    lib = get_lib()
    n = len(jpeg_list)
    blob = b"".join(bytes(j) for j in jpeg_list)
    arr = onp.frombuffer(blob, dtype=onp.uint8)
    lens = onp.array([len(j) for j in jpeg_list], onp.int64)
    offs = onp.zeros(n, onp.int64)
    onp.cumsum(lens[:-1], out=offs[1:]) if n > 1 else None
    out = onp.empty((n, 3, out_h, out_w), onp.float32)
    meanp = (onp.asarray(mean, onp.float32) if mean is not None else None)
    stdp = (onp.asarray(std, onp.float32) if std is not None else None)
    cx = onp.asarray(crop_x if crop_x is not None else
                     onp.full(n, 0.5), onp.float32)
    cy = onp.asarray(crop_y if crop_y is not None else
                     onp.full(n, 0.5), onp.float32)
    mir = onp.asarray(mirror if mirror is not None else
                      onp.zeros(n), onp.uint8)
    fails = lib.decode_augment_batch(
        _ptr(arr, ctypes.c_uint8), _ptr(offs, ctypes.c_int64),
        _ptr(lens, ctypes.c_int64), n, _ptr(out, ctypes.c_float),
        out_h, out_w,
        _ptr(meanp, ctypes.c_float) if meanp is not None else None,
        _ptr(stdp, ctypes.c_float) if stdp is not None else None,
        _ptr(cx, ctypes.c_float), _ptr(cy, ctypes.c_float),
        _ptr(mir, ctypes.c_uint8), resize_short, num_threads)
    return out, int(fails)
