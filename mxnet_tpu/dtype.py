"""Dtype mapping and the reference's on-disk type flags.

Type flag values mirror mshadow (3rdparty/mshadow/mshadow/base.h:307-314)
so ``.params`` files are bit-compatible with the reference.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as onp

# mshadow type_flag <-> numpy dtype (base.h:307-314)
TYPE_FLAG_TO_NP = {
    0: onp.dtype("float32"),
    1: onp.dtype("float64"),
    2: onp.dtype("float16"),
    3: onp.dtype("uint8"),
    4: onp.dtype("int32"),
    5: onp.dtype("int8"),
    6: onp.dtype("int64"),
    7: onp.dtype("bool"),
}
NP_TO_TYPE_FLAG = {v: k for k, v in TYPE_FLAG_TO_NP.items()}
# bfloat16 has no reference flag; saved as float32 on disk.

_STR_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bfloat16": "bfloat16",
}


def normalize_dtype(dtype, default="float32"):
    """Accept str / numpy dtype / jnp dtype / None -> canonical numpy dtype
    object (bfloat16 handled via jnp)."""
    if dtype is None:
        dtype = default
    if isinstance(dtype, str):
        dtype = _STR_ALIASES.get(dtype, dtype)
    if dtype in ("bfloat16", jnp.bfloat16):
        return jnp.bfloat16
    return onp.dtype(dtype)


def dtype_name(dtype) -> str:
    d = normalize_dtype(dtype)
    return "bfloat16" if d == jnp.bfloat16 else onp.dtype(d).name
