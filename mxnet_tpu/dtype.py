"""Dtype mapping and the reference's on-disk type flags.

Type flag values mirror mshadow (3rdparty/mshadow/mshadow/base.h:307-314)
so ``.params`` files are bit-compatible with the reference.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as onp

# mshadow type_flag <-> numpy dtype (base.h:307-314)
TYPE_FLAG_TO_NP = {
    0: onp.dtype("float32"),
    1: onp.dtype("float64"),
    2: onp.dtype("float16"),
    3: onp.dtype("uint8"),
    4: onp.dtype("int32"),
    5: onp.dtype("int8"),
    6: onp.dtype("int64"),
    7: onp.dtype("bool"),
}
NP_TO_TYPE_FLAG = {v: k for k, v in TYPE_FLAG_TO_NP.items()}
# bfloat16 has no reference flag; saved as float32 on disk.
# float8_e4m3fn / float8_e5m2 (round 19) likewise: no mshadow flag,
# saved as float32 on disk (ndarray._save_one's not-in-NP_TO_TYPE_FLAG
# widening), full-precision in-memory via ml_dtypes.

_STR_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bfloat16": "bfloat16",
    # fp8 spellings; bare "fp8"/"float8" means the forward/weight
    # format e4m3 (e5m2 is the gradient format and is always named)
    "fp8": "float8_e4m3fn",
    "float8": "float8_e4m3fn",
    "e4m3": "float8_e4m3fn",
    "fp8_e4m3": "float8_e4m3fn",
    "float8_e4m3": "float8_e4m3fn",
    "e5m2": "float8_e5m2",
    "fp8_e5m2": "float8_e5m2",
}

_FLOAT8_NAMES = ("float8_e4m3fn", "float8_e5m2")


def float8_supported() -> bool:
    """True when this jax/ml_dtypes build carries the float8 types."""
    return hasattr(jnp, "float8_e4m3fn") and hasattr(jnp, "float8_e5m2")


def _float8(name):
    """The jnp float8 scalar type, or a loud MXNetError — never a
    silent fp32 fallback — when this build lacks ml_dtypes float8."""
    if not float8_supported():
        from .base import MXNetError

        raise MXNetError(
            f"dtype {name!r} requires ml_dtypes float8 support, which "
            f"this jax build does not provide; install a jax/ml_dtypes "
            f"with float8_e4m3fn/float8_e5m2 or use bfloat16")
    return getattr(jnp, name)


def normalize_dtype(dtype, default="float32"):
    """Accept str / numpy dtype / jnp dtype / None -> canonical numpy dtype
    object (bfloat16/float8 handled via jnp)."""
    if dtype is None:
        dtype = default
    if isinstance(dtype, str):
        dtype = _STR_ALIASES.get(dtype, dtype)
    if dtype in ("bfloat16", jnp.bfloat16):
        return jnp.bfloat16
    for name in _FLOAT8_NAMES:
        if dtype == name or (float8_supported()
                             and dtype == getattr(jnp, name)):
            return _float8(name)
    return onp.dtype(dtype)


def dtype_name(dtype) -> str:
    d = normalize_dtype(dtype)
    # bfloat16/float8 are jnp scalar types; numpy names them correctly
    # via the ml_dtypes dtype registration
    return "bfloat16" if d == jnp.bfloat16 else onp.dtype(d).name
