"""Testing utilities — assertion helpers, random data, and the
finite-difference gradient checker.

Reference parity: python/mxnet/test_utils.py — ``assert_almost_equal``,
``check_numeric_gradient`` (:981), ``check_symbolic_forward`` /
``check_symbolic_backward``, ``check_consistency`` (dtype ladder), and
the random tensor helpers.  The numeric gradient is the independent
oracle for autograd: central differences of the op's forward, compared
against the framework's analytic (vjp) gradients.
"""
from __future__ import annotations

import numpy as onp

from .base import MXNetError
from .context import Context, cpu, current_context

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal",
    "almost_equal", "same", "rand_shape_2d", "rand_shape_3d",
    "rand_shape_nd", "rand_ndarray", "random_arrays", "numeric_grad",
    "check_numeric_gradient", "check_symbolic_forward",
    "check_symbolic_backward", "check_consistency", "simple_forward",
    "enable_x64", "write_rec_corpus", "corrupt_rec",
]


def enable_x64():
    """Context manager enabling 64-bit jax types, on any jax release:
    ``jax.enable_x64`` became a top-level context manager only in
    recent jax; 0.4.x wheels carry the identical manager under
    ``jax.experimental``.  Used by the f64 reference rungs of the
    dtype ladder and the FD gradient sweeps."""
    import jax

    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(True)
    from jax.experimental import enable_x64 as _ex64

    return _ex64()

_DEFAULT_RTOL = {
    onp.dtype(onp.float16): 1e-2,
    onp.dtype(onp.float32): 1e-4,
    onp.dtype(onp.float64): 1e-5,
}
_DEFAULT_ATOL = {
    onp.dtype(onp.float16): 1e-3,
    onp.dtype(onp.float32): 1e-5,
    onp.dtype(onp.float64): 1e-8,
}


def default_context() -> Context:
    """Reference: test_utils.py:58."""
    return current_context()


def set_default_context(ctx: Context):
    Context._default = ctx


def _to_numpy(a):
    from .ndarray import NDArray

    if isinstance(a, NDArray):
        return a.asnumpy()
    return onp.asarray(a)


def same(a, b):
    return onp.array_equal(_to_numpy(a), _to_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _to_numpy(a), _to_numpy(b)
    rtol = rtol if rtol is not None else _DEFAULT_RTOL.get(a.dtype, 1e-4)
    atol = atol if atol is not None else _DEFAULT_ATOL.get(a.dtype, 1e-5)
    return onp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Reference: test_utils.py assert_almost_equal with tolerance ladder."""
    an, bn = _to_numpy(a), _to_numpy(b)
    rtol = rtol if rtol is not None else _DEFAULT_RTOL.get(an.dtype, 1e-4)
    atol = atol if atol is not None else _DEFAULT_ATOL.get(an.dtype, 1e-5)
    if an.shape != bn.shape:
        raise AssertionError(
            f"shape mismatch: {names[0]}{an.shape} vs {names[1]}{bn.shape}")
    if onp.allclose(an.astype(onp.float64), bn.astype(onp.float64),
                    rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    err = onp.abs(an.astype(onp.float64) - bn.astype(onp.float64))
    denom = onp.abs(bn.astype(onp.float64)) + atol
    rel = err / denom
    idx = onp.unravel_index(onp.argmax(rel), rel.shape)
    raise AssertionError(
        f"{names[0]} and {names[1]} differ: max rel err {rel.max():.3e} at "
        f"{idx} ({an[idx]!r} vs {bn[idx]!r}), rtol={rtol}, atol={atol}")


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, dtype="float32", ctx=None, low=-1.0, high=1.0):
    from . import ndarray as nd

    data = onp.random.uniform(low, high, size=shape).astype(dtype)
    return nd.array(data, ctx=ctx or default_context())


def random_arrays(*shapes):
    arrays = [onp.random.randn(*s).astype(onp.float32) if s else
              onp.float32(onp.random.randn()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Reference: test_utils.py simple_forward — one-shot symbol eval."""
    from . import ndarray as nd

    ctx = ctx or default_context()
    args = {k: nd.array(v, ctx=ctx) for k, v in inputs.items()}
    exe = sym.bind(ctx, args=args)
    outs = exe.forward(is_train=is_train)
    outs = [o.asnumpy() for o in outs]
    return outs[0] if len(outs) == 1 else outs


def numeric_grad(f, args, eps=1e-3, out_grads=None, wrt=None):
    """Central-difference gradients of ``f(*args) -> array`` w.r.t. each
    numpy array in ``args``.

    out_grads: cotangent(s) to contract the output jacobian with; defaults
    to all-ones (matching executor.backward default).  wrt: arg indices
    to differentiate (others return zero gradients without paying the
    2-evaluations-per-element cost).  Reference: test_utils.py
    numeric_grad used by check_numeric_gradient (:981).
    """
    import jax

    # owned C-contiguous float64 copies: perturbation writes below go
    # through reshape(-1) views and must reach the evaluated buffer
    # (and must never mutate the caller's arrays)
    args = [onp.array(a, dtype=onp.float64, order="C", copy=True)
            if onp.issubdtype(onp.asarray(a).dtype, onp.floating)
            else onp.asarray(a) for a in args]

    def eval_f(xs):
        # full fp32 matmul precision: on TPU the MXU default is bf16,
        # which would swallow the +-eps/2 perturbations entirely
        with jax.default_matmul_precision("highest"):
            out = f(*[x.astype(onp.float32) if onp.issubdtype(x.dtype,
                      onp.floating) else x for x in xs])
        outs = out if isinstance(out, (tuple, list)) else (out,)
        outs = [_to_numpy(o).astype(onp.float64) for o in outs]
        if out_grads is None:
            return sum(o.sum() for o in outs)
        ogs = out_grads if isinstance(out_grads, (tuple, list)) \
            else (out_grads,)
        return sum((o * onp.asarray(g, dtype=onp.float64)).sum()
                   for o, g in zip(outs, ogs))

    grads = []
    for i, a in enumerate(args):
        if not onp.issubdtype(a.dtype, onp.floating) or \
                (wrt is not None and i not in wrt):
            grads.append(onp.zeros_like(a, dtype=onp.float64))
            continue
        g = onp.zeros_like(a)
        flat = a.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps / 2
            fp = eval_f(args)
            flat[j] = orig - eps / 2
            fm = eval_f(args)
            flat[j] = orig
            gflat[j] = (fp - fm) / eps
        grads.append(g)
    return grads


def check_numeric_gradient(sym_or_fn, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True,
                           ctx=None, wrt=None, **op_params):
    """Verify analytic gradients against finite differences.

    Reference: test_utils.py:981.  Accepts either a Symbol (bound and
    backward-ed through the executor) or a callable/op-name (run through
    eager autograd) — both exercise the REAL user paths, with numpy
    central differences as the independent oracle.
    """
    from . import autograd
    from . import ndarray as nd
    from .symbol import Symbol

    ctx = ctx or default_context()
    atol = atol if atol is not None else rtol * 1e-1

    if isinstance(sym_or_fn, Symbol):
        sym = sym_or_fn
        if isinstance(location, (list, tuple)):
            location = {k: v for k, v in
                        zip(sym.list_arguments(), location)}
        args = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
        grad_nodes = grad_nodes or list(location.keys())
        args_grad = {k: nd.zeros(args[k].shape, ctx=ctx)
                     for k in grad_nodes}
        aux = {k: nd.array(v, ctx=ctx)
               for k, v in (aux_states or {}).items()}
        exe = sym.bind(ctx, args=args, args_grad=args_grad,
                       aux_states=aux)
        outs = exe.forward(is_train=use_forward_train)
        out_grads = [nd.ones(o.shape, ctx=ctx) for o in outs]
        exe.backward(out_grads if len(out_grads) > 1 else out_grads[0])
        analytic = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

        names = sym.list_arguments()

        # ONE reusable no-grad executor for the whole numeric sweep:
        # the old simple_forward-per-probe re-bound a fresh executor —
        # a fresh jit cache, so XLA recompiled the graph for EVERY
        # +-eps evaluation (2 per element; an LSTM-projection FD check
        # paid ~200 compiles ~= 83 s).  Adopting the perturbed values
        # into one executor compiles once and replays.
        eval_exe = sym.bind(
            ctx, args={k: nd.array(v, ctx=ctx)
                       for k, v in location.items()},
            grad_req="null",
            aux_states={k: nd.array(v, ctx=ctx)
                        for k, v in (aux_states or {}).items()})

        def f(*xs):
            f_outs = eval_exe.forward(is_train=use_forward_train,
                                      **dict(zip(names, xs)))
            f_outs = [o.asnumpy() for o in f_outs]
            return f_outs[0] if len(f_outs) == 1 else f_outs

        loc_list = [location[k] for k in names]
        keep_idx = {i for i, k in enumerate(names) if k in grad_nodes}
        numeric = numeric_grad(f, loc_list, eps=numeric_eps, wrt=keep_idx)
        numeric = {k: g for k, g in zip(names, numeric)
                   if k in grad_nodes}
    else:
        fn = sym_or_fn
        if isinstance(fn, str):
            opname = fn
            fn = lambda *xs: nd.invoke(opname, list(xs), **op_params)  # noqa: E731
        if isinstance(location, dict):
            location = list(location.values())
        arrs = [nd.array(v, ctx=ctx) for v in location]
        for a in arrs:
            a.attach_grad()
        with autograd.record():
            out = fn(*arrs)
            outs = out if isinstance(out, (tuple, list)) else [out]
            head = outs[0]
        if len(outs) > 1:
            autograd.backward(
                outs, head_grads=[nd.ones(o.shape, ctx=ctx) for o in outs])
        else:
            head.backward(nd.ones(head.shape, ctx=ctx))
        keep = set(range(len(arrs))) if wrt is None else set(wrt)
        analytic = {i: a.grad.asnumpy() for i, a in enumerate(arrs)
                    if i in keep}
        numeric = {i: g for i, g in
                   enumerate(numeric_grad(fn, location, eps=numeric_eps,
                                          wrt=keep))
                   if i in keep}

    for k in analytic:
        assert_almost_equal(
            analytic[k], numeric[k], rtol=rtol, atol=atol,
            names=(f"analytic_grad[{k}]", f"numeric_grad[{k}]"))
    return analytic


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Reference: test_utils.py check_symbolic_forward."""
    from . import ndarray as nd

    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = {k: v for k, v in zip(sym.list_arguments(), location)}
    args = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    aux = {k: nd.array(v, ctx=ctx) for k, v in (aux_states or {}).items()}
    exe = sym.bind(ctx, args=args, aux_states=aux)
    outs = exe.forward(is_train=False)
    expected = expected if isinstance(expected, (list, tuple)) else [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(o, e, rtol=rtol, atol=atol,
                            names=("forward", "expected"))
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Reference: test_utils.py check_symbolic_backward."""
    from . import ndarray as nd

    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = {k: v for k, v in zip(sym.list_arguments(), location)}
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym.list_arguments(), expected)}
    args = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    args_grad = {k: nd.zeros(args[k].shape, ctx=ctx) for k in expected}
    aux = {k: nd.array(v, ctx=ctx) for k, v in (aux_states or {}).items()}
    exe = sym.bind(ctx, args=args, args_grad=args_grad, aux_states=aux,
                   grad_req=grad_req)
    exe.forward(is_train=True)
    ogs = [nd.array(g, ctx=ctx) for g in (
        out_grads if isinstance(out_grads, (list, tuple)) else [out_grads])]
    exe.backward(ogs if len(ogs) > 1 else ogs[0])
    for k, e in expected.items():
        assert_almost_equal(exe.grad_dict[k], e, rtol=rtol, atol=atol,
                            names=(f"grad[{k}]", f"expected[{k}]"))
    return {k: exe.grad_dict[k].asnumpy() for k in expected}


def check_consistency(sym, ctx_list=None, dtypes=("float64", "float32"),
                      location=None, rtol=None, atol=None, scale=1.0):
    """Reference: test_utils.py check_consistency (~:1259): run the same
    symbol across a dtype ladder (the reference's cpu-vs-gpu axis has no
    TPU analog — one XLA program serves every backend — so the dtype axis
    carries the check) and compare outputs against the widest dtype.
    """
    from . import ndarray as nd

    ctxs = ctx_list or [default_context()] * len(dtypes)
    if location is None:
        location = {
            k: onp.random.normal(scale=scale, size=s).astype(onp.float64)
            for k, s in zip(sym.list_arguments(),
                            _infer_arg_shapes(sym))
        }
    results = []
    for ctx, dtype in zip(ctxs, dtypes):
        args = {k: nd.array(onp.asarray(v).astype(dtype), ctx=ctx)
                for k, v in location.items()}
        exe = sym.bind(ctx, args=args)
        outs = exe.forward(is_train=False)
        results.append([o.asnumpy().astype(onp.float64) for o in outs])
    ref = results[0]
    for res, dtype in list(zip(results, dtypes))[1:]:
        dt = onp.dtype(dtype)
        for r, e in zip(res, ref):
            assert_almost_equal(
                r, e, rtol=rtol or _DEFAULT_RTOL.get(dt, 1e-3) * 10,
                atol=atol or _DEFAULT_ATOL.get(dt, 1e-4) * 10,
                names=(f"out[{dtype}]", f"out[{dtypes[0]}]"))
    return results


def _infer_arg_shapes(sym):
    shapes, _, _ = sym.infer_shape_partial()
    return shapes


# ------------------------------------------ data-plane fault corpora
def write_rec_corpus(path, n=32, size=16, seed=23, labels=None,
                     quality=90):
    """Write a deterministic .rec shard of random JPEGs for data-plane
    drills (bench ``data_plane`` phase, ``tools/chaos.py`` rec
    scenarios, corruption tests).  ``labels`` maps a record ordinal to
    its float label (default: the ordinal itself).  Returns the
    per-record byte offsets — what :func:`corrupt_rec` seeks by.

    JPEGs are encoded via PIL, not ``pack_img`` — cv2 is absent from
    the CI environment, and these corpora feed tier-1 tests, the bench
    ``data_plane`` phase and the chaos rec scenarios."""
    import io as _io

    from PIL import Image

    from . import recordio

    w = recordio.MXRecordIO(path, "w")
    offsets = []
    rng = onp.random.RandomState(seed)
    try:
        for i in range(n):
            img = (rng.rand(size, size, 3) * 255).astype("uint8")
            bio = _io.BytesIO()
            Image.fromarray(img).save(bio, format="JPEG",
                                      quality=quality)
            offsets.append(w.tell())
            lab = float(labels(i)) if labels is not None else float(i)
            w.write(recordio.pack(
                recordio.IRHeader(0, lab, i, 0), bio.getvalue()))
    finally:
        w.close()
    return offsets


def corrupt_rec(path, offsets, torn=(), unpack=(), decode=()):
    """Seed the three data-plane corruption shapes into a .rec written
    by :func:`write_rec_corpus` (record indices per style):

    * ``torn``   — garbled frame magic (framing-level; the resync
      reader must skip to the next boundary);
    * ``unpack`` — a 0xFFFFFFFF IRHeader flag (frame parses,
      ``recordio.unpack`` raises);
    * ``decode`` — the JPEG payload smeared with a non-magic pattern
      (unpack succeeds, image decode fails).

    ONE corruption recipe shared by every harness, so what chaos
    injects and what bench measures cannot drift apart."""
    with open(path, "r+b") as f:
        for i in torn:
            f.seek(offsets[i])
            f.write(b"\xde\xad\xbe\xef")
        for i in unpack:
            f.seek(offsets[i] + 8)  # past magic+lrec, into the header
            f.write(b"\xff\xff\xff\xff")
        for i in decode:
            f.seek(offsets[i] + 36)  # into the JPEG payload
            f.write(b"\x55" * 48)
