"""Global PRNG key plumbing.

Reference parity: per-device seedable generators
(include/mxnet/random_generator.h, ResourceRequest::kRandom resource.h:42).
TPU-native redesign: JAX threaded PRNG keys.  Eager ops split a global key;
under jit tracing (CachedOp / executor) a *traced* key is installed in a
scope and sub-keys are derived with fold_in so the compiled program stays
pure and reproducible.
"""
from __future__ import annotations

import contextlib
import threading

import jax


class _RngState(threading.local):
    def __init__(self):
        # lazy: creating a key initializes the XLA backend, which must
        # not happen at import time (jax.distributed.initialize has to
        # run first in multi-host processes)
        self.key = None
        self.trace_key = None
        self.trace_counter = 0


_S = _RngState()


def seed(seed_state: int, ctx="all"):
    """mx.random.seed equivalent (python/mxnet/random.py)."""
    _S.key = jax.random.key(int(seed_state))


def take_key():
    """A fresh PRNG key for one random op invocation."""
    if _S.trace_key is not None:
        k = jax.random.fold_in(_S.trace_key, _S.trace_counter)
        _S.trace_counter += 1
        return k
    if _S.key is None:
        _S.key = jax.random.key(0)
    _S.key, sub = jax.random.split(_S.key)
    return sub


@contextlib.contextmanager
def trace_key_scope(key):
    """Install a traced key while tracing a jitted program."""
    prev_k, prev_c = _S.trace_key, _S.trace_counter
    _S.trace_key, _S.trace_counter = key, 0
    try:
        yield
    finally:
        _S.trace_key, _S.trace_counter = prev_k, prev_c
