"""Net rewrite — a calibrated fp32 Gluon net becomes an int8 program.

Reference parity: python/mxnet/contrib/quantization.py ``quantize_graph``
— the graph pass that swaps eligible FullyConnected / Convolution /
Pooling / Flatten nodes for ``quantized_*`` ops with
``quantize_v2`` / ``requantize`` / ``dequantize`` stitching, leaving
everything else fp32.  TPU-native shape: the pass runs over the Gluon
block tree — each eligible leaf is replaced by a wrapper block that
holds the ORIGINAL layer as a child (the fp32 fallback arm) plus its
weights pre-quantized to symmetric int8, and whose forward either

* runs the int8 program: calibrated ``quantize_v2`` on the input,
  ``quantized_fully_connected`` / ``quantized_conv`` accumulating int32
  on the MXU (``preferred_element_type``), calibrated ``requantize`` /
  ``dequantize`` on the way out; or
* falls back to the wrapped fp32 layer,

decided at TRACE time by the autotune variant registry
(``quantized_fc`` / ``quantized_conv`` in ``autotune.VARIANT_OPS``) —
so quantization is adopted per (op, shape, platform) only where the
in-step race measured a win, with ``MXNET_QUANTIZE`` as the hand
override (round-9 precedence ladder).

Round 19 adds a THIRD arm to the per-op race: fp8.  The same wrapper
also bakes an e4m3 copy of its weight (plus the f32 bias and the
weight amax — fp8 needs only amax out of the calibrated range), and
``_arm()`` dispatches "fp32" / "int8" / "fp8" per trace.  The fp8 arm
speaks real-domain f32 at both boundaries: matmul/conv accumulate f32
(no requantize triple exists for fp8), so q-triple stitching never
engages for it and mixed per-layer decisions keep composing — an int8
triple arriving from upstream is dequantized first.

Stitching: inside a (Hybrid)Sequential, consecutive quantized layers
pass the quantized triple ``(int8 data, min, max)`` straight through —
no dequantize/quantize pair between them; Pooling/Flatten wrappers are
range-preserving pass-throughs that only engage when their input
arrives quantized.  A wrapper that receives a quantized triple while
its own decision says fp32 dequantizes first, so MIXED per-layer
decisions always compose correctly.
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from ..gluon.block import HybridBlock

__all__ = ["quantize_net", "tune_quantized", "QuantizedDense",
           "QuantizedConv", "QuantizedPooling", "QuantizedFlatten",
           "quantized_layers"]

_INT8_RANGE = 127.0
_FP8_MAX = 448.0  # e4m3fn finite max


def _quantize_weight(arr):
    """Symmetric per-tensor int8 of a weight array (host-side, once at
    rewrite): returns (int8 numpy, min, max) with max = |w|_inf so the
    quantized op's scale recovery is exact."""
    w = onp.asarray(arr, dtype="float32")
    amax = float(onp.abs(w).max()) or 1.0
    q = onp.clip(onp.rint(w * (_INT8_RANGE / amax)),
                 -127, 127).astype("int8")
    return q, -amax, amax


def _quantize_weight_fp8(arr):
    """Symmetric per-tensor e4m3 of a weight (host-side, once at
    rewrite): the weight is scaled onto the full ±448 e4m3 range and
    clipped BEFORE the cast (e4m3fn overflows to NaN, not inf).
    Returns (e4m3 NDArray, amax)."""
    from .. import ndarray as nd

    w = onp.asarray(arr, dtype="float32")
    amax = float(onp.abs(w).max()) or 1.0
    scaled = onp.clip(w * (_FP8_MAX / amax), -_FP8_MAX, _FP8_MAX)
    return nd.array(scaled).astype("float8_e4m3fn"), amax


def _is_qtensor(x):
    return isinstance(x, (list, tuple)) and len(x) == 3


class _QuantizedLayer(HybridBlock):
    """Shared wrapper machinery: the original layer rides as the
    ``_orig`` child (its Parameters stay collectable — the fp32
    fallback arm and checkpoint compatibility), int8 constants live as
    plain NDArray attributes baked into the traced program, and the
    int8-vs-fp32 decision is consulted per trace through the autotune
    registry."""

    #: name in autotune.VARIANT_OPS ("quantized_fc"/"quantized_conv");
    #: None = structural (pooling/flatten follow their input's form)
    variant_op = None
    _mxnet_quantized = True

    def __init__(self, orig, in_range=None, out_range=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._orig = orig
        self._in_range = tuple(float(v) for v in in_range) \
            if in_range else None
        self._out_range = tuple(float(v) for v in out_range) \
            if out_range else None
        #: stitching flags set by quantize_net's Sequential pass
        self.emit_q = False
        self.accept_q = False

    def _arm(self):
        """Trace-time adoption decision, three-way since round 19:
        "fp32" / "int8" / "fp8", via the autotune precedence ladder
        (force scope > MXNET_QUANTIZE > cached per-program winner >
        default int8 — the layer was rewritten on purpose)."""
        if self.variant_op is None:
            return "int8"  # structural wrappers follow their input form
        from .. import autotune as _at

        v = _at.variant_choice(self.variant_op, default=True)
        if v == "fp8":
            from ..dtype import _float8

            _float8("float8_e4m3fn")  # loud when this build lacks fp8
            return "fp8"
        return "int8" if v else "fp32"

    def _use_int8(self):
        """Back-compat shim over :meth:`_arm` (pre-round-19 callers)."""
        return self._arm() == "int8"

    def _dequant(self, F, q):
        from .. import ndarray as nd

        return nd.invoke("_contrib_dequantize", list(q))

    def _quant_in(self, F, x):
        """fp32 input -> calibrated (int8, min, max) triple."""
        from .. import ndarray as nd

        if self._in_range is None:
            return nd.invoke("_contrib_quantize_v2", [x])
        return nd.invoke("_contrib_quantize_v2", [x],
                         min_calib_range=self._in_range[0],
                         max_calib_range=self._in_range[1])

    def _quant_in_fp8(self, F, x):
        """fp32 input -> (e4m3, amax) pair — calibrated when the
        collector saw this layer (fp8 reuses the int8 collector's
        range; only its amax is consumed)."""
        from .. import ndarray as nd

        if self._in_range is None:
            return nd.invoke("_contrib_quantize_fp8", [x])
        return nd.invoke("_contrib_quantize_fp8", [x],
                         min_calib_range=self._in_range[0],
                         max_calib_range=self._in_range[1])

    def export_dtypes(self):
        """dtype strings of the weights THIS wrapper bakes into an
        exported int8 program (deploy.export_model's param_dtypes
        metadata reads these instead of the shadowed fp32 originals)."""
        return []

    def __repr__(self):
        return f"{type(self).__name__}({self._orig!r})"


class _QuantizedCompute(_QuantizedLayer):
    """Shared machinery of the WEIGHTED wrappers (Dense/Conv): int8
    weight+bias baking at construction, and the one forward skeleton —
    adoption consult, q-triple/fp32 input adaptation, the int8 op
    (subclass ``_invoke``), then requantize-to-triple or
    dequantize(+activation) on the way out."""

    def _bake_weights(self, w_param, b_param, n_out):
        from .. import ndarray as nd
        from ..dtype import float8_supported

        wq, wmin, wmax = _quantize_weight(w_param.data().asnumpy())
        self._wq = nd.array(wq, dtype="int8")
        self._wmin, self._wmax = nd.array([wmin]), nd.array([wmax])
        self._no_bias = b_param is None
        if self._no_bias:
            bq, bmin, bmax = onp.zeros(n_out, "int8"), -1.0, 1.0
        else:
            bq, bmin, bmax = _quantize_weight(b_param.data().asnumpy())
        self._bq = nd.array(bq, dtype="int8")
        self._bmin, self._bmax = nd.array([bmin]), nd.array([bmax])
        # fp8 arm constants (round 19): e4m3 weight + its amax; bias
        # stays f32, added in the real domain after the f32-accumulating
        # matmul.  Only the arm the trace takes gets baked into the
        # program — the others are inert host attributes.  Skipped on
        # builds without float8 (_arm() raises loudly if fp8 is then
        # requested; the int8/fp32 arms must keep working).
        if float8_supported():
            self._w8, w8_amax = _quantize_weight_fp8(
                w_param.data().asnumpy())
            self._w8_amax = nd.array([w8_amax])
            self._b32 = nd.array(onp.zeros(n_out, "float32")) \
                if self._no_bias else nd.array(onp.asarray(
                    b_param.data().asnumpy(), "float32"))

    def _invoke(self, q):
        """Run the int8 op on the quantized input triple ``q``;
        returns the (int32 acc, min, max) triple."""
        raise NotImplementedError

    def _invoke_fp8(self, q):
        """Run the fp8 op on the (e4m3 data, amax) pair ``q``;
        returns the real-domain f32 output (bias already added)."""
        raise NotImplementedError

    def hybrid_forward(self, F, x):
        from .. import ndarray as nd

        q_in = _is_qtensor(x)
        arm = self._arm()
        if arm == "fp32":
            return self._orig(self._dequant(F, x) if q_in else x)
        if arm == "fp8":
            # real-domain f32 at both boundaries: an int8 triple from
            # upstream is dequantized first, and no requantize triple
            # is ever emitted — downstream wrappers treat the f32
            # output like any fp32 input, so mixed decisions compose
            xf = self._dequant(F, x) if q_in else x
            out = self._invoke_fp8(self._quant_in_fp8(F, xf))
            act = getattr(self._orig, "act", None)
            return act(out) if act is not None else out
        q = tuple(x) if q_in else self._quant_in(F, x)
        acc, omin, omax = self._invoke(q)
        act = getattr(self._orig, "act", None)
        if self.emit_q and act is None:
            kw = {}
            if self._out_range is not None:
                kw = {"min_calib_range": self._out_range[0],
                      "max_calib_range": self._out_range[1]}
            return list(nd.invoke("_contrib_requantize",
                                  [acc, omin, omax], **kw))
        out = nd.invoke("_contrib_dequantize", [acc, omin, omax])
        return act(out) if act is not None else out

    def export_dtypes(self):
        arm = self._arm()
        if arm == "fp8":
            return ["float8_e4m3fn"] + \
                ([] if self._no_bias else ["float32"])
        if arm == "int8":
            return ["int8"] if self._no_bias else ["int8", "int8"]
        return []


class QuantizedDense(_QuantizedCompute):
    """Quantized Dense: calibrated input quantize + int8 x int8 -> int32
    FC (``_contrib_quantized_fully_connected``), requantized to int8
    when the next layer consumes quantized data, dequantized to fp32
    otherwise; OR the fp8 arm (e4m3 x e4m3 -> f32, round 19); the
    wrapped fp32 Dense is the fallback arm."""

    variant_op = "quantized_fc"

    def __init__(self, dense, in_range=None, out_range=None, **kw):
        super().__init__(dense, in_range, out_range, **kw)
        self._units = int(dense.weight.shape[0])
        self._flatten = bool(dense._flatten)
        self._bake_weights(dense.weight, dense.bias, self._units)

    def _invoke(self, q):
        from .. import ndarray as nd

        return nd.invoke(
            "_contrib_quantized_fully_connected",
            [q[0], self._wq, self._bq, q[1], q[2],
             self._wmin, self._wmax, self._bmin, self._bmax],
            num_hidden=self._units, no_bias=self._no_bias,
            flatten=self._flatten)

    def _invoke_fp8(self, q):
        from .. import ndarray as nd

        return nd.invoke(
            "_contrib_fp8_fully_connected",
            [q[0], self._w8, self._b32, q[1], self._w8_amax],
            num_hidden=self._units, no_bias=self._no_bias,
            flatten=self._flatten)


class QuantizedConv(_QuantizedCompute):
    """Quantized convolution (``_contrib_quantized_conv`` /
    ``_contrib_fp8_conv``): channel-first layouts only (the quantized
    ops' dimension numbers); same adoption / stitching contract as
    :class:`QuantizedDense`."""

    variant_op = "quantized_conv"

    def __init__(self, conv, in_range=None, out_range=None, **kw):
        super().__init__(conv, in_range, out_range, **kw)
        if conv._channel_last:
            raise MXNetError(
                f"{conv.name}: channel-last convolutions are not "
                "quantizable (int8 conv is NCHW/NCW)")
        k = conv._kwargs
        self._conv_kw = dict(
            kernel=tuple(k["kernel"]), num_filter=int(k["num_filter"]),
            stride=tuple(k["stride"]), pad=tuple(k["pad"]),
            dilate=tuple(k["dilate"]), num_group=int(k["num_group"]))
        self._bake_weights(conv.weight, conv.bias,
                           self._conv_kw["num_filter"])

    def _invoke(self, q):
        from .. import ndarray as nd

        return nd.invoke(
            "_contrib_quantized_conv",
            [q[0], self._wq, self._bq, q[1], q[2],
             self._wmin, self._wmax, self._bmin, self._bmax],
            no_bias=self._no_bias, **self._conv_kw)

    def _invoke_fp8(self, q):
        from .. import ndarray as nd

        return nd.invoke(
            "_contrib_fp8_conv",
            [q[0], self._w8, self._b32, q[1], self._w8_amax],
            no_bias=self._no_bias, **self._conv_kw)


class QuantizedPooling(_QuantizedLayer):
    """Range-preserving int8 pooling: engages only when the input
    arrives as a quantized triple (a standalone quantize-pool-dequant
    sandwich would only add error); fp32 inputs run the wrapped
    layer."""

    def __init__(self, pool, **kw):
        super().__init__(pool, **kw)
        k = pool._kwargs
        self._pool_kw = dict(
            kernel=tuple(k["kernel"]), pool_type=k["pool_type"],
            global_pool=bool(k["global_pool"]),
            stride=tuple(k["stride"]), pad=tuple(k["pad"]),
            pooling_convention=k["pooling_convention"])

    def hybrid_forward(self, F, x):
        from .. import ndarray as nd

        if not _is_qtensor(x):
            return self._orig(x)
        q = nd.invoke("_contrib_quantized_pooling", list(x),
                      **self._pool_kw)
        if self.emit_q:
            return list(q)
        return self._dequant(F, q)


class QuantizedFlatten(_QuantizedLayer):
    """int8 flatten — pure pass-through of the quantization range."""

    def hybrid_forward(self, F, x):
        from .. import ndarray as nd

        if not _is_qtensor(x):
            return self._orig(x)
        q = nd.invoke("_contrib_quantized_flatten", list(x))
        if self.emit_q:
            return list(q)
        return self._dequant(F, q)


def _can_emit_q(wrapper):
    """True when the wrapper can hand an int8 triple to its successor
    (a fused activation forces the fp32 boundary)."""
    if isinstance(wrapper, (QuantizedPooling, QuantizedFlatten)):
        return True
    return getattr(wrapper._orig, "act", None) is None


def _eligible(child, calib, excluded):
    """Which wrapper class (or None) this leaf swaps to under the
    calibration result."""
    from ..gluon.nn.basic_layers import Dense, Flatten
    from ..gluon.nn.conv_layers import _Conv, _Pooling

    if child.name in excluded:
        return None
    if isinstance(child, Dense):
        return QuantizedDense if child.name in calib else None
    if isinstance(child, _Conv):
        if child._op_name != "Convolution" or child._channel_last:
            return None
        return QuantizedConv if child.name in calib else None
    if isinstance(child, _Pooling):
        kw = child._kwargs
        if kw["pool_type"] not in ("max", "avg"):
            return None
        if kw.get("count_include_pad") is False:
            return None  # the int8 pooling op has no exclude-pad path
        return QuantizedPooling
    if isinstance(child, Flatten):
        return QuantizedFlatten
    return None


def quantized_layers(net):
    """Every quantized wrapper under ``net`` (rewrite introspection /
    the deploy metadata scan)."""
    found = []

    def _walk(block):
        if getattr(block, "_mxnet_quantized", False):
            found.append(block)
            return  # never descend into the shadowed fp32 original
        for child in block._children.values():
            _walk(child)

    _walk(net)
    return found


def quantize_net(net, calib, excluded_names=()):
    """Rewrite ``net`` IN PLACE: every calibrated Dense/Conv leaf (and
    every Pooling/Flatten adjacent to one inside a Sequential) becomes
    its quantized wrapper; everything else — norms, activations,
    embeddings, channel-last convs, excluded names — stays fp32.
    Returns ``net``.

    ``calib`` is the :class:`~.calibrate.CalibrationResult`;
    ``excluded_names`` extends its exclusion set (union — either
    escape hatch wins)."""
    from ..gluon.nn.basic_layers import HybridSequential, Sequential

    excluded = set(excluded_names) | set(calib.excluded)
    swapped = []

    def _swap_in(parent, name, child, cls):
        if cls in (QuantizedPooling, QuantizedFlatten):
            wrapper = cls(child)
        else:
            wrapper = cls(child, in_range=calib.range(child.name, "in"),
                          out_range=calib.range(child.name, "out"))
        parent._children[name] = wrapper
        # attribute-style blocks (self.fc = Dense(...)) resolve
        # children through __dict__, not _children — swap both
        for attr, val in list(vars(parent).items()):
            if val is child:
                object.__setattr__(parent, attr, wrapper)
        swapped.append(wrapper)
        return wrapper

    def _walk(parent):
        seq = isinstance(parent, (Sequential, HybridSequential))
        for name, child in list(parent._children.items()):
            cls = _eligible(child, calib, excluded)
            if cls in (QuantizedPooling, QuantizedFlatten) and not seq:
                cls = None  # chain-only layers need a Sequential seam
            if cls is not None:
                _swap_in(parent, name, child, cls)
            else:
                _walk(child)
        if seq:
            _stitch(list(parent._children.values()))

    def _stitch(children):
        """Consecutive wrappers exchange int8 triples directly; a
        pooling/flatten wrapper only counts once something upstream
        actually produces int8 (a chain must START at a conv/fc)."""
        for i, cur in enumerate(children[:-1]):
            nxt = children[i + 1]
            if not (getattr(cur, "_mxnet_quantized", False)
                    and getattr(nxt, "_mxnet_quantized", False)):
                continue
            if not _can_emit_q(cur):
                continue
            if isinstance(cur, (QuantizedPooling, QuantizedFlatten)) \
                    and not cur.accept_q:
                continue  # nothing quantized flows into cur anyway
            cur.emit_q = True
            nxt.accept_q = True

    _walk(net)
    n_q = len([w for w in swapped
               if not isinstance(w, (QuantizedPooling,
                                     QuantizedFlatten))])
    if n_q == 0:
        raise MXNetError(
            "quantize_net: no quantizable layer carries a calibrated "
            "range (check excluded_names / the calibration data)")
    try:
        from .. import telemetry

        telemetry.quantize("rewrite", mode=calib.mode,
                           layers=len(swapped),
                           excluded=len(excluded))
    except Exception:
        pass  # telemetry must never kill a rewrite
    return net


def tune_quantized(net, sample_x, iters=8, level=None):
    """Adoption by measurement (round-9 contract): race the rewritten
    net's int8 AND fp8 arms against fp32 INSIDE one jitted chained run
    of the real inference forward — ``quantized_fc`` and
    ``quantized_conv`` race independently (greedy, earlier winners
    pinned; each now carries three variants), winners
    persist in ``autotune.json`` keyed (op, input shape, dtype,
    platform, mesh) and apply at every later trace through
    ``program_scope`` (CachedOp, make_train_step, export_model).
    A warm cache answers without compiling anything.

    Returns the per-op report ``{op: {"winner", "cached"/"timings"}}``
    (empty when autotune is off)."""
    import jax
    import jax.numpy as jnp

    from .. import autotune as _at
    from ..parallel import functionalize

    lvl = _at.autotune_level() if level is None else int(level)
    if lvl < 1:
        return {}
    present = {w.variant_op for w in quantized_layers(net)
               if w.variant_op is not None}
    race = [op for op in ("quantized_conv", "quantized_fc")
            if op in present]
    if not race:
        return {}
    params, apply_fn = functionalize(net, train=False)
    x = jnp.asarray(onp.asarray(
        sample_x._data if hasattr(sample_x, "_data") else sample_x))
    try:
        plat = jax.local_devices()[0].platform
    except Exception:
        plat = None

    def body(carry, i):
        y = apply_fn(params, carry)
        # thread a zero-valued dependency through the carry so the
        # fori_loop iterations serialize (chain_time methodology)
        return carry + (jnp.sum(y) * 0).astype(carry.dtype)

    report = {}
    decided = {}
    for op in race:
        def measure(_value, _decided=dict(decided)):
            with _at.force(**_decided):
                return _at.chain_time(body, x, iters=iters)

        winner, info = _at.tune(
            op, x.shape, x.dtype, _at.VARIANT_OPS[op], measure,
            platform=plat, level=lvl)
        if winner is not None:
            decided[op] = _at.VARIANT_OPS[op][winner]
            report[op] = {"winner": winner, **info}
    try:
        from .. import telemetry

        telemetry.quantize(
            "race", mode="",
            layers=len([r for r in report.values()
                        if r["winner"] != "fp32"]),
            excluded=0)
    except Exception:
        pass
    return report
