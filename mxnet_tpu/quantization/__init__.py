"""Quantized inference subsystem (round 18; fp8 arm round 19).

Reference parity: ``mxnet.contrib.quantization`` (SURVEY:
src/operator/quantization/, 6,057 LoC) — the calibrate -> graph-rewrite
-> int8-execute pipeline, carried here all the way to a SERVED
artifact:

1. :func:`calibrate` runs a calibration iterator through the trained
   Gluon block (forward hooks) or Module (symbol-internals taps),
   collecting per-tensor ranges — ``naive`` min/max or ``entropy``
   KL-optimal thresholds — with ``excluded_names`` as the per-layer
   escape hatch.
2. :func:`quantize_net` rewrites eligible layers into
   ``quantized_conv`` / ``quantized_fully_connected`` /
   ``quantized_pooling`` / ``quantized_flatten`` wrappers with
   calibrated ``quantize_v2`` / ``requantize`` / ``dequantize``
   stitching and fp32 fallback for everything else.
3. :func:`tune_quantized` races the int8 AND fp8 arms against fp32
   inside a jitted chained run of the real forward (autotune
   VARIANT_OPS ``quantized_fc`` / ``quantized_conv``, three variants
   each since round 19); adoption is per (op, shape, platform) by
   MEASUREMENT, winners persisted in ``autotune.json``;
   ``MXNET_QUANTIZE`` is the hand override (``fp8`` pins the fp8
   program).  The fp8 arm reuses the int8 calibration ranges — e4m3
   scaling needs only the amax (``CalibrationResult.amax``) — and its
   matmul/conv accumulate f32 with real-domain f32 outputs, so no
   requantize stage exists for it.
4. ``deploy.export_model`` serializes the quantized program into the
   CRC-framed ``.mxje`` format (now carrying ``quantized`` /
   ``param_dtypes`` header metadata) and
   ``serving.ModelServer.from_artifact`` serves it AOT —
   load-not-retrace, retrace counter 0 — with ``fleet.rolling_swap``
   upgrading a live fleet fp32 -> int8 under traffic.

Round 17 adds the KV-cache arm of the same story: :mod:`.kv` holds the
per-(token, head) symmetric int8 quantize/dequantize pair plus the
page-byte accounting the generative server's paged cache
(serving.kvcache) admits sequences against — gated, like the layer
rewrites above, by a measured output-agreement floor.

Env knobs (config.py): ``MXNET_QUANTIZE`` (hand override of the
adoption race), ``MXNET_QUANT_CALIB_MODE``,
``MXNET_QUANT_CALIB_BATCHES``; the KV cache reads ``MXNET_KV_DTYPE``.
"""
from .calibrate import (  # noqa: F401
    QUANTIZABLE_OPS,
    CalibrationResult,
    TensorStats,
    calibrate,
    calibrate_block,
    calibrate_module,
    optimal_threshold,
)
from .kv import (  # noqa: F401
    kv_dequantize,
    kv_page_bytes,
    kv_quantize,
)
from .rewrite import (  # noqa: F401
    QuantizedConv,
    QuantizedDense,
    QuantizedFlatten,
    QuantizedPooling,
    quantize_net,
    quantized_layers,
    tune_quantized,
)

__all__ = [
    "calibrate", "calibrate_block", "calibrate_module",
    "CalibrationResult", "TensorStats", "optimal_threshold",
    "QUANTIZABLE_OPS", "quantize_net", "tune_quantized",
    "quantized_layers", "QuantizedDense", "QuantizedConv",
    "QuantizedPooling", "QuantizedFlatten",
    "kv_quantize", "kv_dequantize", "kv_page_bytes",
]
