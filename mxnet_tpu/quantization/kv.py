"""int8 KV-cache quantization (round 17).

The generative decode server's HBM-capacity lever: KV-cache pages
stored int8 with one symmetric scale per (token, head) — the same
``_INT8_RANGE`` convention as the quantized-inference operators
(ops/quantization_ops), applied along the head_dim axis that a single
attention dot consumes.  Per-(token, head) granularity is the sweet
spot for a cache: one fp32 scale amortizes over head_dim int8 values
(head_dim >= 8 gives >= 2.6x the fp32 footprint), while per-tensor
scales would let one outlier token crush every other token's
resolution.

Consumed by serving.kvcache.PagedKVPool (storage) and
ops.flash_attention.paged_decode_attention (dequantize-on-gather
inside the jitted decode step).  Adoption is gated like the PR-13
int8 programs: the generative server's warmup probes per-token output
agreement against an fp32-cache arm and falls back below the floor.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops.quantization_ops import _INT8_RANGE, _minmax_scale

__all__ = ["kv_quantize", "kv_dequantize", "kv_page_bytes"]


def kv_quantize(x):
    """Symmetric int8 quantization of ``(..., head_dim)`` KV vectors.

    Returns ``(q, scale)`` — ``q`` int8 with x ~= q * scale, ``scale``
    fp32 of shape ``x.shape[:-1]`` (one per (token, head) when fed the
    cache's ``(..., tokens, heads, head_dim)`` layout).  An all-zero
    vector quantizes to zeros with scale 0 and round-trips exactly.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    inv, amax = _minmax_scale(-amax, amax)  # inv = 127/amax (1.0 at 0)
    q = jnp.clip(jnp.round(xf * inv[..., None]), -127, 127).astype(
        jnp.int8)
    return q, amax / _INT8_RANGE


def kv_dequantize(q, scale):
    """Inverse of :func:`kv_quantize`: ``q * scale`` back to fp32."""
    return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


def kv_page_bytes(layers, page_tokens, heads, head_dim, dtype):
    """Bytes one physical KV page costs in the given storage dtype —
    the page-pool accounting the token-budget admission (and the
    int8-capacity acceptance ratio) is measured from.  K and V both
    stored; int8 carries one fp32 scale per (token, head)."""
    per_tok_head = {"int8": head_dim * 1 + 4,
                    "float32": head_dim * 4,
                    "bfloat16": head_dim * 2}.get(str(dtype))
    if per_tok_head is None:
        raise ValueError(f"unsupported KV-cache dtype {dtype!r}")
    return 2 * layers * page_tokens * heads * per_tok_head
