"""Calibration pass — per-tensor activation ranges for int8 rewrite.

Reference parity: python/mxnet/contrib/quantization.py — the
``quantize_model(calib_mode=...)`` pipeline's collection half:
``calib_mode="naive"`` records running min/max per observed tensor
(`_LayerOutputMinMaxCollector`); ``calib_mode="entropy"`` accumulates
an absolute-value histogram per tensor (`_LayerHistogramCollector`,
bin-widening ``combine_histogram``) and picks the KL-divergence-optimal
symmetric threshold (`_get_optimal_threshold`) so rare outliers do not
stretch the int8 grid over empty space.

Two front doors, one collector:

* **Gluon blocks** — forward pre/post hooks on every quantizable leaf
  layer (Dense / channel-first Conv / Pooling / Flatten) observe the
  layer's input and output while the calibration iterator runs
  eagerly (hybridized jit caches bypass hooks, so hybridization is
  suspended for the passes and restored after).
* **Module** — the symbol graph's quantizable nodes are tapped through
  ``get_internals()``: one group executor binds the module's trained
  params and evaluates every tap per calibration batch (the
  reference's ``collect_layer_output`` path — executor-side, no
  hooks).

The result maps LAYER NAME -> {"in": (min, max), "out": (min, max)};
``excluded_names`` is the per-layer escape hatch the rewrite honors
too.
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError

__all__ = ["CalibrationResult", "TensorStats", "calibrate",
           "calibrate_block", "calibrate_module", "optimal_threshold",
           "QUANTIZABLE_OPS"]

#: symbol-graph ops the calibration taps / the rewrite targets — the
#: reference's quantizable-op registry (quantized_conv/fc/pooling/
#: flatten) projected onto this framework's op names
QUANTIZABLE_OPS = ("FullyConnected", "Convolution", "Pooling",
                   "Flatten")

_NBINS = 2048  # histogram resolution of the entropy collector
#: widening cap: past this many bins the histogram REBINS back to
#: _NBINS over the new range instead of growing (a near-zero first
#: batch must not make a later normal-magnitude batch allocate a
#: range/width-ratio-sized array)
_MAX_BINS = 8 * _NBINS


def optimal_threshold(hist, hist_th, num_quantized_bins=255,
                      max_sweeps=96):
    """KL-divergence-optimal symmetric threshold over an absolute-value
    histogram spanning ``[0, hist_th]`` (reference
    ``_get_optimal_threshold``): sweep candidate clip points, quantize
    the clipped distribution into ``num_quantized_bins`` levels, expand
    back, and keep the threshold minimizing KL(p || q).  ``max_sweeps``
    strides the sweep so a fat histogram stays O(bins * sweeps)."""
    hist = onp.asarray(hist, dtype="float64").copy()
    nbins = len(hist)
    if nbins == 0 or hist.sum() == 0 or hist_th <= 0:
        return float(hist_th) if hist_th > 0 else 1.0
    if nbins <= num_quantized_bins:
        return float(hist_th)
    # drop the zero bin from the divergence: zeros (the ReLU spike —
    # often MOST of the mass) are exactly representable at any
    # threshold, so their count carries no information about where to
    # clip, but left in they drown the saturation penalty and the
    # sweep happily clips real tail mass
    hist[0] = 0.0
    if hist.sum() == 0:
        return float(hist_th)
    width = hist_th / nbins
    stops = range(num_quantized_bins, nbins + 1,
                  max(1, (nbins - num_quantized_bins) // max_sweeps))
    best_kl, best_stop = onp.inf, nbins
    for stop in stops:
        # p: the clipped distribution — everything past the candidate
        # threshold SATURATES into the last kept bin (what the int8
        # clamp does to real data)
        raw = hist[:stop]
        p = raw.copy()
        p[-1] += hist[stop:].sum()
        total = p.sum()
        if total == 0:
            continue
        # q: the int8 representation of the IN-RANGE counts only —
        # quantize raw into num_quantized_bins levels and expand back
        # uniformly over each level's NONZERO source bins.  Built from
        # raw, NOT p: piling the outlier mass into q too would hide
        # the saturation cost and every sweep would pick the smallest
        # threshold (KL(p||p) = 0)
        factor = stop / num_quantized_bins
        q = onp.zeros(stop)
        for i in range(num_quantized_bins):
            lo = int(round(i * factor))
            hi = max(int(round((i + 1) * factor)), lo + 1)
            chunk = raw[lo:hi]
            nz = chunk > 0
            if nz.any():
                q[lo:hi] = onp.where(nz, chunk.sum() / nz.sum(), 0.0)
        pn = p / total
        qsum = q.sum()
        if qsum == 0:
            continue
        qn = q / qsum
        mask = pn > 0
        kl = float((pn[mask]
                    * onp.log(pn[mask]
                              / onp.maximum(qn[mask], 1e-12))).sum())
        if kl < best_kl:
            best_kl, best_stop = kl, stop
    return float(best_stop * width)


class TensorStats:
    """Running distribution of ONE observed tensor: min/max always;
    an absolute-value histogram (bin-widening on range growth, the
    reference's ``combine_histogram``) when entropy mode will need
    it."""

    def __init__(self, collect_hist=False):
        self.min = onp.inf
        self.max = -onp.inf
        self.batches = 0
        self._collect_hist = collect_hist
        self._hist = None
        self._th = 0.0

    def update(self, arr):
        arr = onp.asarray(arr)
        if arr.size == 0:
            return
        self.batches += 1
        self.min = min(self.min, float(arr.min()))
        self.max = max(self.max, float(arr.max()))
        if not self._collect_hist:
            return
        a = onp.abs(arr.astype("float32", copy=False)).ravel()
        amax = float(a.max())
        if self._hist is None:
            self._th = max(amax, 1e-12)
            self._hist = onp.zeros(_NBINS, dtype="int64")
        elif amax > self._th:
            # widen by whole bins (bin width preserved, so earlier
            # counts stay exactly placed) — reference combine_histogram
            width = self._th / len(self._hist)
            nbins = int(onp.ceil(amax / width))
            if nbins > _MAX_BINS:
                # range grew too far for exact widening (e.g. a
                # near-zero first batch seeded a tiny threshold):
                # REBIN the existing counts proportionally into
                # _NBINS bins over the new range instead of
                # allocating range/width bins
                new_th = float(amax)
                old_edges = onp.linspace(0.0, self._th,
                                         len(self._hist) + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2
                idx = onp.minimum(
                    (centers / new_th * _NBINS).astype("int64"),
                    _NBINS - 1)
                rebinned = onp.zeros(_NBINS, dtype="int64")
                onp.add.at(rebinned, idx, self._hist)
                self._hist = rebinned
                self._th = new_th
            else:
                widened = onp.zeros(nbins, dtype="int64")
                widened[:len(self._hist)] = self._hist
                self._hist = widened
                self._th = nbins * width
        h, _ = onp.histogram(a, bins=len(self._hist),
                             range=(0.0, self._th))
        self._hist += h

    def range(self, mode):
        """The calibrated (min, max) under ``mode``.  naive = running
        min/max; entropy = the KL-optimal symmetric threshold."""
        if self.batches == 0:
            raise MXNetError("TensorStats.range() before any update")
        if mode == "naive":
            return float(self.min), float(self.max)
        if mode != "entropy":
            raise MXNetError(f"unknown calib mode {mode!r}")
        if self._hist is None:
            raise MXNetError(
                "entropy range requested from a naive-mode collector")
        th = optimal_threshold(self._hist, self._th)
        return -th, th


class CalibrationResult:
    """Per-layer calibrated ranges: ``result[name]`` ->
    ``{"in": (min, max), "out": (min, max)}`` plus the collection
    metadata the rewrite stamps into telemetry."""

    def __init__(self, ranges, mode, num_batches, excluded=()):
        self._ranges = dict(ranges)
        self.mode = mode
        self.num_batches = num_batches
        self.excluded = tuple(excluded)

    def __contains__(self, name):
        return name in self._ranges

    def __getitem__(self, name):
        return self._ranges[name]

    def __len__(self):
        return len(self._ranges)

    def __iter__(self):
        return iter(self._ranges)

    def layers(self):
        return sorted(self._ranges)

    def range(self, name, which="in"):
        """The calibrated (min, max) of ``name``'s input or output, or
        None when the layer was never observed."""
        entry = self._ranges.get(name)
        return entry.get(which) if entry else None

    def amax(self, name, which="in"):
        """``max(|min|, |max|)`` of the calibrated range — the one
        statistic the fp8 arm needs (round 19: per-tensor symmetric
        e4m3 scaling consumes only the amax out of the same collected
        range the int8 arm uses — no second calibration pass).  None
        when the layer was never observed."""
        r = self.range(name, which)
        if r is None:
            return None
        return max(abs(float(r[0])), abs(float(r[1])))

    def as_dict(self):
        return {n: dict(e) for n, e in self._ranges.items()}


def _calib_defaults(mode, num_batches):
    from ..config import get_env

    if mode is None:
        mode = get_env("MXNET_QUANT_CALIB_MODE")
    if mode not in ("naive", "entropy"):
        raise MXNetError(
            f"unknown calib_mode {mode!r} (naive | entropy)")
    if num_batches is None:
        num_batches = int(get_env("MXNET_QUANT_CALIB_BATCHES"))
    return mode, max(1, int(num_batches))


def _quantizable_blocks(net, excluded_names):
    """(name, block) of every quantizable LEAF layer under ``net`` —
    the same eligibility set the rewrite swaps — excluding names the
    caller fenced off."""
    from ..gluon.nn.basic_layers import Dense, Flatten
    from ..gluon.nn.conv_layers import _Conv, _Pooling

    found = []

    def _walk(block):
        for child in block._children.values():
            if isinstance(child, (Dense, Flatten, _Pooling)) or (
                    isinstance(child, _Conv)
                    and child._op_name == "Convolution"):
                if child.name not in excluded_names:
                    found.append((child.name, child))
            else:
                _walk(child)

    _walk(net)
    return found


def calibrate_block(net, calib_data, num_batches=None, mode=None,
                    excluded_names=()):
    """Run ``calib_data`` through a Gluon ``net`` eagerly, observing
    every quantizable layer's input and output through forward hooks.
    ``calib_data`` yields batches (NDArray / numpy).  Returns a
    :class:`CalibrationResult`."""
    from .. import ndarray as nd
    from ..gluon.block import HybridBlock

    mode, num_batches = _calib_defaults(mode, num_batches)
    targets = _quantizable_blocks(net, set(excluded_names))
    if not targets:
        # fail BEFORE paying the calibration forwards, like the
        # module path — an all-excluded / no-eligible-leaf net would
        # otherwise surface as a misdirected rewrite error later
        raise MXNetError(
            "calibrate: no quantizable layers in the net (check "
            "excluded_names / layer eligibility)")
    collect_hist = mode == "entropy"
    stats = {name: {"in": TensorStats(collect_hist),
                    "out": TensorStats(collect_hist)}
             for name, _ in targets}

    # hybridized (jit-cached) forwards bypass child hooks: run the
    # calibration passes eagerly, restoring hybridization after
    hybrid = []

    def _dehybridize(block):
        if isinstance(block, HybridBlock) and block._active:
            hybrid.append(block)
            block._active = False
        for child in block._children.values():
            _dehybridize(child)

    _dehybridize(net)
    handles = []
    try:
        for name, child in targets:
            def pre(blk, inputs, _s=stats[name]["in"]):
                if inputs and isinstance(inputs[0], nd.NDArray):
                    _s.update(inputs[0].asnumpy())

            def post(blk, inputs, out, _s=stats[name]["out"]):
                o = out[0] if isinstance(out, (list, tuple)) else out
                if isinstance(o, nd.NDArray):
                    _s.update(o.asnumpy())

            handles.append(child.register_forward_pre_hook(pre))
            handles.append(child.register_forward_hook(post))
        seen = 0
        for batch in calib_data:
            if seen >= num_batches:
                break
            x = batch if isinstance(batch, nd.NDArray) else \
                nd.array(onp.asarray(batch))
            net(x)
            seen += 1
    finally:
        for h in handles:
            h.detach()
        for b in hybrid:
            b._active = True
    if seen == 0:
        raise MXNetError("calibrate: calib_data yielded no batches")
    return _finish(stats, mode, seen, excluded_names)


def calibrate_module(mod, calib_data, num_batches=None, mode=None,
                     excluded_names=()):
    """Calibrate a bound :class:`~mxnet_tpu.module.Module`: tap the
    data input and output of every quantizable symbol node through one
    internals group executor bound over the module's trained params,
    and fold each calibration batch through the collector.  Batches
    are raw arrays for the module's single data input."""
    from .. import ndarray as nd
    from .. import symbol as sym_mod

    mode, num_batches = _calib_defaults(mode, num_batches)
    sym = mod._symbol
    arg_params, aux_params = mod.get_params()
    excluded = set(excluded_names)

    taps = []  # (layer_name, which, Symbol)
    for node in sym._topo():
        if node.op in QUANTIZABLE_OPS and node.name not in excluded:
            data_node, data_idx = node.inputs[0]
            taps.append((node.name, "in",
                         sym_mod.Symbol(data_node, data_idx)))
            taps.append((node.name, "out", sym_mod.Symbol(node, 0)))
    if not taps:
        raise MXNetError(
            "calibrate: no quantizable layers in the module symbol")
    group = sym_mod.Group([t[2] for t in taps])

    collect_hist = mode == "entropy"
    stats = {}
    for name, which, _ in taps:
        stats.setdefault(name, {})[which] = TensorStats(collect_hist)

    data_names = list(getattr(mod, "_data_names", ("data",)))
    params = dict(arg_params)
    seen = 0
    for batch in calib_data:
        if seen >= num_batches:
            break
        x = batch if isinstance(batch, nd.NDArray) else \
            nd.array(onp.asarray(batch))
        ex = group.bind(args={data_names[0]: x, **params},
                        aux_states=dict(aux_params))
        outs = ex.forward(is_train=False)
        for (name, which, _), o in zip(taps, outs):
            stats[name][which].update(o.asnumpy())
        seen += 1
    if seen == 0:
        raise MXNetError("calibrate: calib_data yielded no batches")
    return _finish(stats, mode, seen, excluded_names)


def _finish(stats, mode, num_batches, excluded_names):
    ranges = {}
    for name, entry in stats.items():
        if not any(s.batches for s in entry.values()):
            continue  # layer never executed (dead branch)
        ranges[name] = {
            which: s.range(mode)
            for which, s in entry.items() if s.batches
        }
    result = CalibrationResult(ranges, mode, num_batches,
                               excluded_names)
    try:
        from .. import telemetry

        telemetry.quantize("calibrate", mode=mode, layers=len(ranges),
                           excluded=len(result.excluded))
    except Exception:
        pass  # telemetry must never kill a calibration pass
    return result


def calibrate(net_or_module, calib_data, num_batches=None, mode=None,
              excluded_names=()):
    """Front door: dispatch on the trained thing's kind — a Gluon
    ``Block`` calibrates through forward hooks, a ``Module`` through
    symbol-internals taps.  ``mode`` None follows
    ``MXNET_QUANT_CALIB_MODE``; ``num_batches`` None follows
    ``MXNET_QUANT_CALIB_BATCHES``."""
    from ..gluon.block import Block

    if isinstance(net_or_module, Block):
        return calibrate_block(net_or_module, calib_data,
                               num_batches=num_batches, mode=mode,
                               excluded_names=excluded_names)
    if hasattr(net_or_module, "_symbol"):
        return calibrate_module(net_or_module, calib_data,
                                num_batches=num_batches, mode=mode,
                                excluded_names=excluded_names)
    raise MXNetError(
        "calibrate: expected a gluon Block or a Module, got "
        f"{type(net_or_module).__name__}")
