"""Deployment: serialized compiled inference artifacts.

Reference: include/mxnet/c_predict_api.h:348 + amalgamation/ — the
reference ships a C ABI predictor that loads symbol-JSON + params with
no Python.  TPU-native translation: ``jax.export`` serializes the
traced+lowered StableHLO of a model's forward into a self-contained
artifact; the loader needs jax (any language with a StableHLO runtime
can also consume ``stablehlo_text``), not the model's Python code —
the same deploy-without-model-source contract the predict API serves.

    path = mx.deploy.export_model(net, example_x, "model.mxje")
    f = mx.deploy.load_model(path)     # -> callable on nd/np arrays
    y = f(x)

Artifact framing (round 13): ``export_model`` prepends a fixed-size
header — magic + payload length + CRC32 — so ``load_model`` verifies
integrity BEFORE handing bytes to the deserializer: a truncated or
bit-flipped ``.mxje`` (the torn-upload/partial-download case a model
server restart hits first) raises a clean :class:`MXNetError` naming
the path instead of an opaque deserialization crash.  Headerless
artifacts from earlier rounds still load (magic sniff falls back to
treating the whole file as the payload).

Artifact metadata (round 18): the v2 frame carries a small JSON
metadata segment between the header and the payload — input signature,
``quantized`` flag and ``param_dtypes`` histogram — so operators and
the fleet admission path can tell an int8 (or, round 19, fp8) artifact
from fp32 by reading a few hundred header bytes, WITHOUT deserializing
the StableHLO program.  v1 and headerless artifacts keep loading; their
``artifact_info`` falls back to deserialization (with the new fields
None).
"""
from __future__ import annotations

import json
import struct
import zlib

import numpy as onp

from .base import MXNetError

__all__ = ["export_model", "export_generative", "load_model",
           "load_exported", "load_generative", "stablehlo_text",
           "artifact_info", "read_artifact_meta"]

#: v1 artifact header: magic, then ``<IQ`` = CRC32(payload),
#: len(payload)
_MAGIC = b"MXJE\x01\n"
_HEADER = struct.Struct("<IQ")
#: v2 artifact header (round 18): magic, then ``<IQI`` =
#: CRC32(meta_json + payload), len(payload), len(meta_json); the JSON
#: metadata segment follows the header, the payload follows it
_MAGIC2 = b"MXJE\x02\n"
_HEADER2 = struct.Struct("<IQI")


def _functional_forward(net):
    from .parallel import functionalize

    params, apply_fn = functionalize(net, train=False)
    return params, apply_fn


def _net_meta(net, x, platforms):
    """The v2 header metadata of an export: input signature,
    ``quantized`` (does the program run int8 or fp8 quantized layers)
    and a ``param_dtypes`` histogram of the weights the program
    actually bakes — an fp8 artifact is identified by
    ``float8_e4m3fn`` entries in that histogram, again without any
    deserialization.  Must be computed under the same autotune program scope as
    the export trace: a wrapper whose adoption race picked fp32 bakes
    its fp32 original, and the header must say so — the identity
    describes the PROGRAM, not the net's potential."""
    dtype_counts = {}

    def _count(dt):
        dt = str(dt)
        dtype_counts[dt] = dtype_counts.get(dt, 0) + 1

    quantized = False
    q_layers = 0

    def _walk(block):
        nonlocal quantized, q_layers
        if getattr(block, "_mxnet_quantized", False):
            if block.variant_op is None:
                return  # pooling/flatten pass-through: no weights
            if block._arm() != "fp32":  # int8 OR fp8 (round 19)
                quantized = True
                q_layers += 1
                for dt in block.export_dtypes():
                    _count(dt)
                return  # the shadowed fp32 original is dead here
            _walk(block._orig)  # fp32-armed: its original's weights
            return
        for p in getattr(block, "_reg_params", {}).values():
            try:
                _count(p.dtype)
            except Exception:
                pass
        for child in getattr(block, "_children", {}).values():
            _walk(child)

    try:
        _walk(net)
    except Exception:
        quantized, q_layers, dtype_counts = False, 0, {}
    return {
        "batch": int(x.shape[0]) if x.ndim else 1,
        "item_shape": [int(s) for s in x.shape[1:]],
        "dtype": str(x.dtype),
        "platforms": list(platforms),
        "quantized": bool(quantized),
        "quantized_layers": int(q_layers),
        "param_dtypes": dtype_counts,
    }


def export_model(net, example_input, path, platforms=("cpu", "tpu"),
                 extra_meta=None):
    """Serialize ``net``'s inference forward (weights baked in) to
    ``path`` via jax.export.  ``example_input`` fixes shapes/dtypes
    (ndarray / numpy).  The default multi-platform lowering makes one
    artifact loadable on CPU hosts and TPU workers alike.  Returns
    ``path``.

    ``extra_meta`` (round 18, the online loop): extra JSON-able keys
    merged into the v2 header metadata — ``model_version`` (monotonic)
    and ``stream_cursor`` above all — so ``read_artifact_meta`` can
    answer "which version is this, trained through which sample?"
    from a few hundred header bytes.  Round 20 adds ``trace_anchor``
    (a ``traceparent`` string): the exporting trainer's span context,
    so a rolling-swap can parent its serve-side cutover span on the
    training step that produced the weights.  Reserved structural keys
    (``batch``/``item_shape``/...) cannot be overridden.

    Round 18: a SINGLE-platform export traces under the autotune
    ``program_scope`` keyed on that platform, so persisted variant
    winners — the int8-vs-fp32 quantization race above all — bake
    into the exported program exactly as they would into a live
    CachedOp.  A multi-platform export gets ONE traced program, which
    cannot honor per-platform verdicts: cached winners do NOT apply
    there (the exporting host's CPU verdict must not pin the TPU
    lowering of an AOT artifact forever) — only explicit force scopes
    / ``MXNET_QUANTIZE``-style env overrides decide.  The v2 frame
    records ``quantized``/``param_dtypes`` metadata readable without
    deserialization."""
    import contextlib

    import jax
    from jax import export as jexport

    from . import autotune as _at
    from .ndarray import NDArray

    x = example_input._data if isinstance(example_input, NDArray) \
        else jax.numpy.asarray(onp.asarray(example_input))
    params, apply_fn = _functional_forward(net)

    def infer(xv):
        return apply_fn(params, xv)

    from .resilience.checkpoint import atomic_write_bytes

    scope = _at.program_scope(x.shape, x.dtype,
                              platform=platforms[0]) \
        if len(platforms) == 1 else contextlib.nullcontext()
    with scope:
        exp = jexport.export(
            jax.jit(infer),
            platforms=platforms)(jax.ShapeDtypeStruct(x.shape, x.dtype))
        # metadata under the SAME scope: the quantized/param_dtypes
        # identity must describe what this trace actually baked
        meta_doc = _net_meta(net, x, platforms)
    if extra_meta:
        for k, v in dict(extra_meta).items():
            if k not in meta_doc:
                meta_doc[k] = v
    blob = exp.serialize()
    meta = json.dumps(meta_doc, sort_keys=True).encode("utf-8")
    # the resilience atomic writer (temp + fsync + rename + dir
    # fsync, temp cleaned up on failure) so a crash mid-export can
    # never leave a half-written file at the published path; the
    # header lets the loader verify length+CRC before deserializing
    atomic_write_bytes(
        path,
        _MAGIC2 + _HEADER2.pack(zlib.crc32(meta + blob) & 0xFFFFFFFF,
                                len(blob), len(meta)) + meta + blob,
        inject_point=None)
    if meta_doc.get("quantized"):
        try:
            from . import telemetry

            telemetry.quantize(
                "export", mode="",
                layers=int(meta_doc["quantized_layers"]))
        except Exception:
            pass  # telemetry must never kill an export
    return path


def _flatten_params(tree, prefix=""):
    """Flatten a nested dict/list param pytree into ``{"a/0/b": array}``
    — the npz-friendly shape of a generative artifact payload."""
    flat = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            flat.update(_flatten_params(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            flat.update(_flatten_params(v, f"{prefix}{i}/"))
    else:
        flat[prefix[:-1]] = onp.asarray(tree)
    return flat


def _unflatten_params(flat):
    root = {}
    for key in sorted(flat):
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = flat[key]

    def fix(node):
        if isinstance(node, dict):
            if node and all(k.isdigit() for k in node):
                return [fix(node[str(i)]) for i in range(len(node))]
            return {k: fix(v) for k, v in node.items()}
        return node

    return fix(root)


def export_generative(params, path, *, vocab, layers, heads, head_dim,
                      prompt_buckets=(4, 8, 16), max_new=16,
                      extra_meta=None):
    """Serialize a generative (decoder-only) model into a v2 ``.mxje``
    artifact: the param pytree as the npz payload, the decode
    configuration under a ``"gen"`` metadata key, and
    ``"generative": true`` in the header so ``read_artifact_meta``
    identifies the artifact class without touching the payload.  The
    fleet's :class:`~mxnet_tpu.serving.fleet.ModelHost` builds a
    :class:`~mxnet_tpu.serving.generate.GenerativeServer` from it;
    ``extra_meta`` stamps the same ``model_version``/``stream_cursor``
    identity as :func:`export_model`."""
    import io

    from .resilience.checkpoint import atomic_write_bytes

    flat = _flatten_params(params)
    buf = io.BytesIO()
    onp.savez(buf, **flat)
    blob = buf.getvalue()
    meta_doc = {
        "generative": True,
        # token-stream input signature: what admission/residency
        # reports show for a generative artifact
        "batch": 1,
        "item_shape": [int(max(prompt_buckets))],
        "dtype": "int32",
        "platforms": ["cpu", "tpu"],
        "quantized": False,
        "param_dtypes": _dtype_histogram(flat),
        "gen": {"vocab": int(vocab), "layers": int(layers),
                "heads": int(heads), "head_dim": int(head_dim),
                "prompt_buckets": [int(b) for b in prompt_buckets],
                "max_new": int(max_new)},
    }
    if extra_meta:
        for k, v in dict(extra_meta).items():
            if k not in meta_doc:
                meta_doc[k] = v
    meta = json.dumps(meta_doc, sort_keys=True).encode("utf-8")
    atomic_write_bytes(
        path,
        _MAGIC2 + _HEADER2.pack(zlib.crc32(meta + blob) & 0xFFFFFFFF,
                                len(blob), len(meta)) + meta + blob,
        inject_point=None)
    return path


def _dtype_histogram(flat):
    counts = {}
    for arr in flat.values():
        dt = str(arr.dtype)
        counts[dt] = counts.get(dt, 0) + 1
    return counts


def load_generative(path):
    """Load + verify a generative artifact; returns ``(params, gen)``
    where ``params`` is the decoder param pytree and ``gen`` the
    decode-configuration dict the exporter stamped.  Refuses
    non-generative artifacts with a clean :class:`MXNetError`."""
    import io

    meta, payload = _read_meta_payload(path)
    if not (meta or {}).get("generative"):
        raise MXNetError(
            f"deploy artifact {path!r} is not a generative export "
            "(load it with deploy.load_model / load_exported)")
    try:
        with onp.load(io.BytesIO(payload)) as z:
            flat = {k: z[k] for k in z.files}
    except Exception as e:  # noqa: BLE001 — name the artifact, always
        raise MXNetError(
            f"failed to deserialize generative artifact {path!r}: "
            f"{e!r}") from e
    return _unflatten_params(flat), dict(meta.get("gen") or {})


def _read_meta_payload(path):
    """Read + integrity-check an artifact; returns ``(meta, payload)``
    where ``meta`` is the v2 header metadata dict (None for v1 /
    headerless files).  v2 verifies CRC32 over meta+payload, v1 over
    the payload; headerless (pre-round-13) files pass through whole."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise MXNetError(
            f"cannot read deploy artifact {path!r}: {e}") from e
    if data.startswith(_MAGIC2):
        off = len(_MAGIC2)
        if len(data) < off + _HEADER2.size:
            raise MXNetError(
                f"corrupt deploy artifact {path!r}: truncated header "
                f"({len(data)} bytes)")
        crc, length, meta_len = _HEADER2.unpack_from(data, off)
        body = data[off + _HEADER2.size:]
        if len(body) != meta_len + length:
            raise MXNetError(
                f"corrupt deploy artifact {path!r}: body is "
                f"{len(body)} bytes, header says {meta_len} metadata "
                f"+ {length} payload (truncated or partially written)")
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise MXNetError(
                f"corrupt deploy artifact {path!r}: CRC32 mismatch "
                "(bit rot or torn write)")
        try:
            meta = json.loads(body[:meta_len].decode("utf-8"))
        except ValueError as e:
            raise MXNetError(
                f"corrupt deploy artifact {path!r}: unparseable "
                f"metadata segment ({e})") from e
        return meta, body[meta_len:]
    if not data.startswith(_MAGIC):
        return None, data  # legacy headerless: best-effort load
    off = len(_MAGIC)
    if len(data) < off + _HEADER.size:
        raise MXNetError(
            f"corrupt deploy artifact {path!r}: truncated header "
            f"({len(data)} bytes)")
    crc, length = _HEADER.unpack_from(data, off)
    blob = data[off + _HEADER.size:]
    if len(blob) != length:
        raise MXNetError(
            f"corrupt deploy artifact {path!r}: payload is "
            f"{len(blob)} bytes, header says {length} (truncated or "
            "partially written)")
    if zlib.crc32(blob) & 0xFFFFFFFF != crc:
        raise MXNetError(
            f"corrupt deploy artifact {path!r}: CRC32 mismatch "
            "(bit rot or torn write)")
    return None, blob


def _read_payload(path):
    return _read_meta_payload(path)[1]


def read_artifact_meta(path):
    """The v2 header metadata WITHOUT reading the payload: opens the
    file, reads magic + header + the (small) metadata segment, and
    stops.  No CRC verification — the caller is expected to have
    loaded (and therefore verified) the artifact through
    ``load_exported``/``from_artifact`` already; this is the cheap
    identity probe for residency reports and admission logs.  Returns
    None for v1/headerless artifacts or on any read problem."""
    try:
        with open(path, "rb") as f:
            head = f.read(len(_MAGIC2) + _HEADER2.size)
            if not head.startswith(_MAGIC2) \
                    or len(head) < len(_MAGIC2) + _HEADER2.size:
                return None
            _, _, meta_len = _HEADER2.unpack_from(head, len(_MAGIC2))
            if meta_len > (1 << 20):
                return None  # implausible header: refuse to trust it
            meta = f.read(meta_len)
            if len(meta) != meta_len:
                return None
            doc = json.loads(meta.decode("utf-8"))
            # consumers cache this and .get() into it: anything but
            # an object is not artifact metadata
            return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def load_exported(path):
    """Load + verify an artifact, returning the ``jax.export``
    ``Exported`` object (``.call``, ``.in_avals``, ...) — the handle
    the model server warm-starts from without retracing."""
    from jax import export as jexport

    meta, blob = _read_meta_payload(path)
    if (meta or {}).get("generative"):
        raise MXNetError(
            f"deploy artifact {path!r} is a generative export — load "
            "it with deploy.load_generative (the fleet's ModelHost "
            "does this automatically)")
    try:
        return jexport.deserialize(blob)
    except MXNetError:
        raise
    except Exception as e:  # noqa: BLE001 — name the artifact, always
        raise MXNetError(
            f"failed to deserialize deploy artifact {path!r}: {e!r} "
            "(re-export with deploy.export_model; round-13 exports "
            "carry a CRC header that catches corruption before this "
            "point)") from e


def artifact_info(path):
    """Shape/dtype metadata of an artifact's input signature without
    building the runner: ``{"batch", "item_shape", "dtype",
    "platforms", "quantized", "param_dtypes"}`` — what a serving
    bucket plan and the fleet admission path need.  A v2 artifact
    answers from its verified header metadata alone (a few hundred
    bytes, NO deserialization — an operator can tell an int8 artifact
    from fp32 before any program builds); v1/headerless artifacts fall
    back to deserializing, with the round-18 fields None."""
    meta, _ = _read_meta_payload(path)
    if meta is not None:
        return {"batch": int(meta["batch"]),
                "item_shape": tuple(int(s)
                                    for s in meta["item_shape"]),
                "dtype": str(meta["dtype"]),
                "platforms": tuple(meta.get("platforms", ())),
                "quantized": meta.get("quantized"),
                "param_dtypes": meta.get("param_dtypes")}
    exp = load_exported(path)
    aval = exp.in_avals[0]
    return {"batch": int(aval.shape[0]),
            "item_shape": tuple(int(s) for s in aval.shape[1:]),
            "dtype": str(aval.dtype),
            "platforms": tuple(getattr(exp, "platforms", ()) or ()),
            "quantized": None, "param_dtypes": None}


def load_model(path):
    """Load a serialized artifact; returns ``f(x) -> NDArray`` (no
    model Python code needed — the artifact carries the program and
    the weights as constants).  Integrity is verified (CRC header)
    before deserialization; corruption raises :class:`MXNetError`
    naming the path."""
    from .ndarray import NDArray

    exp = load_exported(path)

    def run(x):
        import jax.numpy as jnp

        xv = x._data if isinstance(x, NDArray) else jnp.asarray(
            onp.asarray(x))
        out = exp.call(xv)
        if isinstance(out, (tuple, list)):
            return [NDArray(o) for o in out]
        return NDArray(out)

    return run


def stablehlo_text(net, example_input):
    """The StableHLO MLIR of the inference forward — the
    language-neutral exchange format (any StableHLO runtime can
    compile it; the analog of shipping the amalgamated predictor)."""
    import jax

    from .ndarray import NDArray

    x = example_input._data if isinstance(example_input, NDArray) \
        else jax.numpy.asarray(onp.asarray(example_input))
    params, apply_fn = _functional_forward(net)
    lowered = jax.jit(lambda xv: apply_fn(params, xv)).lower(
        jax.ShapeDtypeStruct(x.shape, x.dtype))
    return lowered.as_text()
