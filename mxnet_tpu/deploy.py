"""Deployment: serialized compiled inference artifacts.

Reference: include/mxnet/c_predict_api.h:348 + amalgamation/ — the
reference ships a C ABI predictor that loads symbol-JSON + params with
no Python.  TPU-native translation: ``jax.export`` serializes the
traced+lowered StableHLO of a model's forward into a self-contained
artifact; the loader needs jax (any language with a StableHLO runtime
can also consume ``stablehlo_text``), not the model's Python code —
the same deploy-without-model-source contract the predict API serves.

    path = mx.deploy.export_model(net, example_x, "model.mxje")
    f = mx.deploy.load_model(path)     # -> callable on nd/np arrays
    y = f(x)

Artifact framing (round 13): ``export_model`` prepends a fixed-size
header — magic + payload length + CRC32 — so ``load_model`` verifies
integrity BEFORE handing bytes to the deserializer: a truncated or
bit-flipped ``.mxje`` (the torn-upload/partial-download case a model
server restart hits first) raises a clean :class:`MXNetError` naming
the path instead of an opaque deserialization crash.  Headerless
artifacts from earlier rounds still load (magic sniff falls back to
treating the whole file as the payload).
"""
from __future__ import annotations

import struct
import zlib

import numpy as onp

from .base import MXNetError

__all__ = ["export_model", "load_model", "load_exported",
           "stablehlo_text", "artifact_info"]

#: artifact header: magic, then ``<IQ`` = CRC32(payload), len(payload)
_MAGIC = b"MXJE\x01\n"
_HEADER = struct.Struct("<IQ")


def _functional_forward(net):
    from .parallel import functionalize

    params, apply_fn = functionalize(net, train=False)
    return params, apply_fn


def export_model(net, example_input, path, platforms=("cpu", "tpu")):
    """Serialize ``net``'s inference forward (weights baked in) to
    ``path`` via jax.export.  ``example_input`` fixes shapes/dtypes
    (ndarray / numpy).  The default multi-platform lowering makes one
    artifact loadable on CPU hosts and TPU workers alike.  Returns
    ``path``."""
    import jax
    from jax import export as jexport

    from .ndarray import NDArray

    x = example_input._data if isinstance(example_input, NDArray) \
        else jax.numpy.asarray(onp.asarray(example_input))
    params, apply_fn = _functional_forward(net)

    def infer(xv):
        return apply_fn(params, xv)

    from .resilience.checkpoint import atomic_write_bytes

    exp = jexport.export(
        jax.jit(infer),
        platforms=platforms)(jax.ShapeDtypeStruct(x.shape, x.dtype))
    blob = exp.serialize()
    # the resilience atomic writer (temp + fsync + rename + dir
    # fsync, temp cleaned up on failure) so a crash mid-export can
    # never leave a half-written file at the published path; the
    # header lets the loader verify length+CRC before deserializing
    atomic_write_bytes(
        path,
        _MAGIC + _HEADER.pack(zlib.crc32(blob) & 0xFFFFFFFF,
                              len(blob)) + blob,
        inject_point=None)
    return path


def _read_payload(path):
    """Read + integrity-check an artifact; returns the serialized
    payload bytes.  Headered files verify length+CRC32; headerless
    (pre-round-13) files pass through whole."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise MXNetError(
            f"cannot read deploy artifact {path!r}: {e}") from e
    if not data.startswith(_MAGIC):
        return data  # legacy headerless artifact: best-effort load
    off = len(_MAGIC)
    if len(data) < off + _HEADER.size:
        raise MXNetError(
            f"corrupt deploy artifact {path!r}: truncated header "
            f"({len(data)} bytes)")
    crc, length = _HEADER.unpack_from(data, off)
    blob = data[off + _HEADER.size:]
    if len(blob) != length:
        raise MXNetError(
            f"corrupt deploy artifact {path!r}: payload is "
            f"{len(blob)} bytes, header says {length} (truncated or "
            "partially written)")
    if zlib.crc32(blob) & 0xFFFFFFFF != crc:
        raise MXNetError(
            f"corrupt deploy artifact {path!r}: CRC32 mismatch "
            "(bit rot or torn write)")
    return blob


def load_exported(path):
    """Load + verify an artifact, returning the ``jax.export``
    ``Exported`` object (``.call``, ``.in_avals``, ...) — the handle
    the model server warm-starts from without retracing."""
    from jax import export as jexport

    blob = _read_payload(path)
    try:
        return jexport.deserialize(blob)
    except MXNetError:
        raise
    except Exception as e:  # noqa: BLE001 — name the artifact, always
        raise MXNetError(
            f"failed to deserialize deploy artifact {path!r}: {e!r} "
            "(re-export with deploy.export_model; round-13 exports "
            "carry a CRC header that catches corruption before this "
            "point)") from e


def artifact_info(path):
    """Shape/dtype metadata of an artifact's input signature without
    building the runner: ``{"batch", "item_shape", "dtype",
    "platforms"}`` — what a serving bucket plan needs."""
    exp = load_exported(path)
    aval = exp.in_avals[0]
    return {"batch": int(aval.shape[0]),
            "item_shape": tuple(int(s) for s in aval.shape[1:]),
            "dtype": str(aval.dtype),
            "platforms": tuple(getattr(exp, "platforms", ()) or ())}


def load_model(path):
    """Load a serialized artifact; returns ``f(x) -> NDArray`` (no
    model Python code needed — the artifact carries the program and
    the weights as constants).  Integrity is verified (CRC header)
    before deserialization; corruption raises :class:`MXNetError`
    naming the path."""
    from .ndarray import NDArray

    exp = load_exported(path)

    def run(x):
        import jax.numpy as jnp

        xv = x._data if isinstance(x, NDArray) else jnp.asarray(
            onp.asarray(x))
        out = exp.call(xv)
        if isinstance(out, (tuple, list)):
            return [NDArray(o) for o in out]
        return NDArray(out)

    return run


def stablehlo_text(net, example_input):
    """The StableHLO MLIR of the inference forward — the
    language-neutral exchange format (any StableHLO runtime can
    compile it; the analog of shipping the amalgamated predictor)."""
    import jax

    from .ndarray import NDArray

    x = example_input._data if isinstance(example_input, NDArray) \
        else jax.numpy.asarray(onp.asarray(example_input))
    params, apply_fn = _functional_forward(net)
    lowered = jax.jit(lambda xv: apply_fn(params, xv)).lower(
        jax.ShapeDtypeStruct(x.shape, x.dtype))
    return lowered.as_text()
