"""Deployment: serialized compiled inference artifacts.

Reference: include/mxnet/c_predict_api.h:348 + amalgamation/ — the
reference ships a C ABI predictor that loads symbol-JSON + params with
no Python.  TPU-native translation: ``jax.export`` serializes the
traced+lowered StableHLO of a model's forward into a self-contained
artifact; the loader needs jax (any language with a StableHLO runtime
can also consume ``stablehlo_text``), not the model's Python code —
the same deploy-without-model-source contract the predict API serves.

    path = mx.deploy.export_model(net, example_x, "model.mxje")
    f = mx.deploy.load_model(path)     # -> callable on nd/np arrays
    y = f(x)
"""
from __future__ import annotations

import numpy as onp

from .base import MXNetError

__all__ = ["export_model", "load_model", "stablehlo_text"]


def _functional_forward(net):
    from .parallel import functionalize

    params, apply_fn = functionalize(net, train=False)
    return params, apply_fn


def export_model(net, example_input, path, platforms=("cpu", "tpu")):
    """Serialize ``net``'s inference forward (weights baked in) to
    ``path`` via jax.export.  ``example_input`` fixes shapes/dtypes
    (ndarray / numpy).  The default multi-platform lowering makes one
    artifact loadable on CPU hosts and TPU workers alike.  Returns
    ``path``."""
    import jax
    from jax import export as jexport

    from .ndarray import NDArray

    x = example_input._data if isinstance(example_input, NDArray) \
        else jax.numpy.asarray(onp.asarray(example_input))
    params, apply_fn = _functional_forward(net)

    def infer(xv):
        return apply_fn(params, xv)

    exp = jexport.export(
        jax.jit(infer),
        platforms=platforms)(jax.ShapeDtypeStruct(x.shape, x.dtype))
    blob = exp.serialize()
    with open(path, "wb") as f:
        f.write(blob)
    return path


def load_model(path):
    """Load a serialized artifact; returns ``f(x) -> NDArray`` (no
    model Python code needed — the artifact carries the program and
    the weights as constants)."""
    from jax import export as jexport

    from .ndarray import NDArray

    with open(path, "rb") as f:
        blob = f.read()
    exp = jexport.deserialize(blob)

    def run(x):
        import jax.numpy as jnp

        xv = x._data if isinstance(x, NDArray) else jnp.asarray(
            onp.asarray(x))
        out = exp.call(xv)
        if isinstance(out, (tuple, list)):
            return [NDArray(o) for o in out]
        return NDArray(out)

    return run


def stablehlo_text(net, example_input):
    """The StableHLO MLIR of the inference forward — the
    language-neutral exchange format (any StableHLO runtime can
    compile it; the analog of shipping the amalgamated predictor)."""
    import jax

    from .ndarray import NDArray

    x = example_input._data if isinstance(example_input, NDArray) \
        else jax.numpy.asarray(onp.asarray(example_input))
    params, apply_fn = _functional_forward(net)
    lowered = jax.jit(lambda xv: apply_fn(params, xv)).lower(
        jax.ShapeDtypeStruct(x.shape, x.dtype))
    return lowered.as_text()
