"""Training callbacks (reference: python/mxnet/callback.py)."""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False,
                      keep_n=None):
    """Checkpoint the Module at the end of every `period` epochs.

    Writes route through the atomic versioned writer
    (resilience.checkpoint): rename-atomic payloads, CRC manifest,
    `latest` pointer.  ``keep_n`` prunes older versions (None keeps
    all, the historical behavior)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1,
                                save_optimizer_states, keep_n=keep_n)

    return _callback


def do_checkpoint(prefix, period=1, keep_n=None):
    """Checkpoint params (+symbol) every `period` epochs (reference
    callback.py:55), atomically (see ``module_checkpoint``)."""
    from . import model

    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            model.save_checkpoint(prefix, iter_no + 1, sym, arg, aux,
                                  keep_n=keep_n)

    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info(
                    "Iter[%d] Batch[%d] Train-%s=%f",
                    param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Log training speed + metrics every `frequent` batches (reference
    callback.py:120).

    Timing uses ``time.perf_counter()`` — a monotonic clock — so an
    NTP step or wall-clock jump during training cannot produce
    negative or absurd samples/sec (``time.time()`` could).  When run
    telemetry is active (``MXNET_RUNLOG``), the reported rate is the
    RunLog's authoritative recent-step-window throughput — the same
    number the run log and metrics textfile carry — instead of a
    second independent measurement."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def _speed(self):
        try:
            from . import telemetry

            rl = telemetry.current()
            if rl is not None:
                # only over the steps of THIS reporting interval
                # (since=tic): an eval loop records no steps so this
                # returns None and we fall back to our own clock, and
                # a window that opened mid-run is not diluted by an
                # eval gap or the previous epoch's steps
                authoritative = rl.recent_throughput(since=self.tic)
                if authoritative is not None:
                    return authoritative
        except Exception:
            pass  # telemetry broken must not silence the log line
        try:
            return (self.frequent * self.batch_size
                    / (time.perf_counter() - self.tic))
        except ZeroDivisionError:
            return float("inf")

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                speed = self._speed()
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(
                        msg, param.epoch, count, speed,
                        *sum(name_value, ()))
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.perf_counter()
        else:
            self.init = True
            self.tic = time.perf_counter()


class ProgressBar:
    """ASCII progress bar (reference ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    def __call__(self, param):
        if not param.eval_metric:
            return
        name_value = param.eval_metric.get_name_value()
        for name, value in name_value:
            logging.info(
                "Epoch[%d] Validation-%s=%f", param.epoch, name, value)
