"""Runtime feature detection + multi-process bring-up.

The reference surfaces compile-time build flags (CUDA/CUDNN/NCCL/
DIST_KVSTORE/..., include/mxnet/libinfo.h:141-190) through
`mx.runtime.feature_list()` (reference python/mxnet/runtime.py:28-90).
The TPU build has no compile-time matrix — capabilities are determined
by the live JAX install — so features are probed at call time instead
of baked in.

This module is also the front door for the elastic multi-process
runtime: :func:`init_distributed` runs the ``resilience.elastic``
bring-up (``jax.distributed.initialize`` with a bounded-retry barrier)
when ``MXNET_ELASTIC``/the launcher env asks for it, and
:func:`distributed_info` reports the live world.  Callers that never
opt in pay nothing — the single-process path returns a local context
without touching ``jax.distributed``.
"""
from __future__ import annotations

import collections

__all__ = ["Feature", "feature_list", "Features", "init_distributed",
           "distributed_info"]


def init_distributed(coordinator=None, num_processes=None,
                     process_id=None, **kw):
    """Multi-process bring-up (idempotent).  Resolves the coordinator
    and process identity from args > ``MXNET_COORDINATOR`` /
    ``MXNET_NUM_PROCESSES`` / ``MXNET_PROCESS_ID`` > the ``DMLC_*``
    launcher contract, retries ``jax.distributed.initialize`` with
    backoff, proves the collective mesh with a barrier, and returns
    the :class:`~mxnet_tpu.resilience.elastic.ElasticContext`.  Must
    run BEFORE the first jax backend touch in a distributed job."""
    from .resilience import elastic

    return elastic.elastic_init(coordinator=coordinator,
                                num_processes=num_processes,
                                process_id=process_id, **kw)


def distributed_info():
    """The live elastic context, or None before ``init_distributed``
    (single-process jobs get a local context once initialized)."""
    from .resilience import elastic

    return elastic.context()


class Feature:
    """One named capability flag (reference runtime.py:28 exposes
    ctypes structs; here a plain object with the same attributes)."""

    def __init__(self, name, enabled):
        self._name = name
        self._enabled = bool(enabled)

    @property
    def name(self):
        return self._name

    @property
    def enabled(self):
        return self._enabled

    def __repr__(self):
        if self.enabled:
            return f"✔ {self.name}"
        return f"✖ {self.name}"


def _probe():
    import jax

    feats = collections.OrderedDict()

    def add(name, on):
        feats[name] = Feature(name, on)

    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:  # backend init can fail in exotic environments
        platforms = set()
    add("TPU", "tpu" in platforms)
    add("CUDA", "gpu" in platforms or "cuda" in platforms)
    add("CPU", True)
    add("XLA", True)
    add("JIT", True)
    add("BF16", True)
    add("INT64_TENSOR_SIZE", True)
    try:
        import jax.experimental.pallas  # noqa: F401
        add("PALLAS", True)
    except Exception:
        add("PALLAS", False)
    add("DIST_KVSTORE", True)  # jax.distributed (kvstore dist modes)
    try:
        from .resilience import elastic

        # enabled = the env asks for multi-process bring-up;
        # initialized state is reported separately below
        add("ELASTIC", elastic.elastic_enabled()
            or elastic.initialized())
    except Exception:
        add("ELASTIC", False)
    add("F16C", True)
    add("SIGNAL_HANDLER", False)
    add("PROFILER", True)
    add("OPENCV", _has_module("cv2"))
    add("MKLDNN", False)
    add("TENSORRT", False)
    add("BLAS_OPEN", False)
    add("LAPACK", True)  # jax.scipy.linalg
    return feats


def _has_module(name):
    import importlib.util

    return importlib.util.find_spec(name) is not None


def feature_list():
    """List capabilities of the current runtime (reference
    runtime.py:51)."""
    return list(_probe().values())


class Features(collections.OrderedDict):
    """OrderedDict of name -> Feature (reference runtime.py:65)."""

    def __init__(self):
        super().__init__([(f.name, f) for f in feature_list()])

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature '{feature_name}' is unknown, "
                               "known features are: "
                               f"{list(self.keys())}")
        return self[feature_name].enabled
