"""Unified run telemetry (observability round).

Public surface:

* :func:`current` — the active :class:`RunLog` or None (``MXNET_RUNLOG``
  env arms it; the unset fast path is two dict lookups).
* :func:`reset` / :func:`close` — (re)arm at a precise program point.
* no-op-safe wire points every subsystem calls: :func:`compile_event`,
  :func:`event`, :func:`count`, :func:`checkpoint_event`,
  :func:`program_report`, :func:`flight_dump`.
* :func:`describe_program` — XLA memory/flop/collective introspection
  of a compiled step.
* :func:`fit_session` — the per-``Module.fit`` session wrapper.
* :mod:`.tracing` — W3C-style distributed trace context + span
  emission (round 20); merged across processes by
  ``tools/tracemerge.py``.
* :mod:`.schema` — the JSONL record contract tests and CI validate.

Env knobs (registered in :mod:`mxnet_tpu.config`): ``MXNET_RUNLOG``,
``MXNET_TELEMETRY_SAMPLE``, ``MXNET_FLIGHTREC_DEPTH``,
``MXNET_METRICS_TEXTFILE``, ``MXNET_TRACE_CONTEXT``,
``MXNET_PROCESS_ROLE``, ``MXNET_PROCESS_RANK``.
"""
from . import numerics, opstats, schema, tracing  # noqa: F401
from .runlog import (  # noqa: F401
    RunLog,
    checkpoint_event,
    close,
    compile_event,
    compile_fingerprint,
    count,
    current,
    data_plane,
    describe_program,
    event,
    find_flight_dumps,
    flight_dump,
    flight_path_for,
    freshness,
    gauge,
    generate,
    heal,
    program_report,
    quantize,
    reset,
)
from .session import FitSession, fit_session  # noqa: F401
from .tracing import TraceContext  # noqa: F401
from .watchdog import (  # noqa: F401
    Watchdog,
    find_stack_dumps,
    stack_path_for,
)

__all__ = [
    "RunLog", "current", "reset", "close", "compile_event",
    "compile_fingerprint", "event", "count", "gauge", "generate",
    "heal", "freshness",
    "data_plane", "quantize", "checkpoint_event", "program_report",
    "flight_dump",
    "flight_path_for", "find_flight_dumps", "describe_program",
    "FitSession",
    "fit_session", "schema", "Watchdog", "stack_path_for",
    "find_stack_dumps", "tracing", "TraceContext",
    "numerics", "opstats",
]
