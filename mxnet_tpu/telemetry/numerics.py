"""In-graph numerics monitor — Monitor 2.0 (``MXNET_NUMERICS``).

The reference Monitor installs a per-op output callback; inside one
fused XLA program those outputs don't exist, so PR 3's NaN guard could
only say *that* a step went non-finite, never *which tensor made it
so*.  This module closes that gap with summary reductions that are
traceable — they compile INTO the step — and cheap enough to run every
step:

``summary(x)`` -> a ``(6,)`` float32 vector
    ``[l2_norm, min, max, nan_count, inf_count, zero_fraction]``
    (l2 over the finite elements so one Inf doesn't erase the norm;
    min/max are raw, so a poisoned tensor shows its NaN/Inf there).

Wire points:

* ``make_train_step`` (armed at BUILD time by ``MXNET_NUMERICS``):
  per-gradient summaries plus the loss ride in the returned optimizer
  state under the reserved ``_numerics`` key — no signature change, no
  host callback, no sync.  The telemetry wrapper reads them back ONLY
  on sampled steps (``MXNET_NUMERICS_SAMPLE``, 0 = follow
  ``MXNET_TELEMETRY_SAMPLE``) and emits ``tensor_stats`` records, so a
  NaN step is *explained* (which tensor, which step) in the run log
  before the guard kills the run.
* ``Module.fit`` (eager executor path): gradients are host-visible
  arrays, so the jitted ``summarize_named`` runs only on sampled steps
  and on every bad step — the diagnosis costs nothing off-sample.
* ``Monitor(stat_func="numerics")`` reports the same six numbers
  through the classic tic/toc protocol.

Unarmed contract: ``MXNET_NUMERICS`` unset means the traced program is
bit-identical to a build without this module (no extra outputs, no
reserved state entry) and the per-step host cost is one captured
boolean check.
"""
from __future__ import annotations

__all__ = ["STAT_FIELDS", "armed", "sample_period", "summary",
           "summarize_tree", "summary_template", "stats_row",
           "summarize_named", "emit", "nonfinite"]

#: order of the packed summary vector
STAT_FIELDS = ("l2", "min", "max", "nan", "inf", "zero_frac")


def armed():
    """``MXNET_NUMERICS`` from the registry (build/arm-time check —
    never on the per-step hot path)."""
    from ..config import get_env

    try:
        return bool(get_env("MXNET_NUMERICS"))
    except Exception:
        return False


def sample_period():
    """Steps between ``tensor_stats`` emissions.  0 = follow
    ``MXNET_TELEMETRY_SAMPLE`` (one knob to rule the sync cadence)."""
    from ..config import get_env

    n = int(get_env("MXNET_NUMERICS_SAMPLE"))
    if n <= 0:
        n = int(get_env("MXNET_TELEMETRY_SAMPLE"))
    return max(1, n)


# ------------------------------------------------------------- traceable
def summary(x):
    """The packed (6,) float32 summary — traceable, fuses into the
    surrounding program as a handful of reductions."""
    import jax.numpy as jnp

    xf = jnp.asarray(x).astype(jnp.float32)
    nan = jnp.isnan(xf).sum().astype(jnp.float32)
    inf = jnp.isinf(xf).sum().astype(jnp.float32)
    finite = jnp.where(jnp.isfinite(xf), xf, 0.0)
    l2 = jnp.sqrt(jnp.sum(finite * finite))
    zero_frac = jnp.mean((xf == 0.0).astype(jnp.float32))
    # raw min/max: a poisoned tensor SHOWS its NaN/Inf here
    return jnp.stack([l2, jnp.min(xf), jnp.max(xf), nan, inf,
                      zero_frac])


def summarize_tree(named):
    """``{name: array}`` -> ``{name: summary(array)}`` (traceable)."""
    return {str(k): summary(v) for k, v in named.items()}


def summary_template(named):
    """Zeros with the summaries' structure — the initial opt_state
    entry (donated pytrees need a stable structure from step 0)."""
    import jax.numpy as jnp

    return {str(k): jnp.zeros((len(STAT_FIELDS),), jnp.float32)
            for k in named}


# ----------------------------------------------------------- host side
def stats_row(vec):
    """One host-read (6,) vector -> the labelled record row."""
    import numpy as onp

    v = onp.asarray(vec, dtype="float64")
    return {"l2": float(v[0]), "min": float(v[1]), "max": float(v[2]),
            "nan": int(v[3]), "inf": int(v[4]),
            "zero_frac": float(v[5])}


def nonfinite(rows):
    """Whether any summarised tensor carried a NaN/Inf element."""
    return any(r["nan"] > 0 or r["inf"] > 0 for r in rows.values())


_EAGER = {"fn": None}


def summarize_named(named):
    """Jitted eager summariser for host-visible tensors (the Module
    path): call ONLY on sampled/bad steps — the computation itself is
    sampled there, not just the readback."""
    import jax

    if _EAGER["fn"] is None:
        _EAGER["fn"] = jax.jit(summarize_tree)
    return _EAGER["fn"]({k: getattr(v, "_data", v)
                         for k, v in named.items()})


def emit(rl, step, named_vecs, where="grad", epoch=None):
    """Read the summary vectors to host and write one ``tensor_stats``
    record (the single device sync the sampled step pays)."""
    rows = {k: stats_row(v) for k, v in named_vecs.items()}
    bad = nonfinite(rows)
    rl.tensor_stats(step, rows, where=where, nonfinite=bad,
                    epoch=epoch)
    return rows, bad
