"""Process-wide run telemetry: the ``RunLog`` every subsystem reports
into (the tentpole of the observability round).

The reference treats observability as a first-class subsystem — a C++
``Profiler`` with lock-free per-thread stat buffers wired into every
engine ``OprBlock``, dumped as one Chrome-trace timeline plus an
aggregate table (src/profiler/profiler.h:251, aggregate_stats.cc).
This module is the TPU-native equivalent for the *run* level: the
subsystems built in earlier rounds (device feed, ZeRO exchange,
autotuner, resilience, PS client, NaN guard) each own private signals;
the RunLog is where they all land, on one clock, with four outputs:

* **JSONL run log** (``MXNET_RUNLOG=path``): one record per step plus
  compile/checkpoint/program/event records — schema in
  :mod:`.schema`.  Step records are appended buffered and flushed on
  every sampled step (and every non-step record), so the tail a hard
  kill can lose is bounded by one sample period — and the flight
  recorder re-dumps exactly those last steps on every managed death
  path anyway.  Every complete line is valid JSON.
* **Chrome-trace lane**: when the profiler is collecting, every step/
  feed-wait/checkpoint span and the throughput/loss counters land in
  ``profiler.dump()``'s timeline next to the op events (and the
  ``jax.profiler`` device capture the same run/stop toggles).
* **compile/memory introspection**: :func:`describe_program` compiles
  a step (or reuses a Compiled/Lowered) and records XLA's
  ``memory_analysis()``/``cost_analysis()`` plus the HLO collective
  counts (``parallel.zero.collective_bytes``) as a ``program_report``.
* **crash flight recorder**: a ring of the last
  ``MXNET_FLIGHTREC_DEPTH`` step records plus config/env/compile
  fingerprints, dumped through the resilience atomic writer on
  SIGTERM drain, NaN-abort, fault-injection crash or an unhandled
  exception inside ``Module.fit`` — the post-mortem a dead run
  otherwise takes to the grave.

Hot-path contract: with ``MXNET_RUNLOG`` unset, :func:`current` is two
dict lookups returning ``None`` and every wire point no-ops — no file
IO, no device syncs.  With it set, an unsampled step costs one dict
build + one list append: serialization (``json.dumps``), the buffered
writes and the flush syscall are all deferred to the next sampled
step (or the next non-step record), and device syncs (loss readback)
happen only every ``MXNET_TELEMETRY_SAMPLE`` steps.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

__all__ = ["RunLog", "current", "reset", "close", "compile_event",
           "compile_fingerprint", "event", "count", "gauge", "heal",
           "quantize", "freshness", "checkpoint_event",
           "program_report", "flight_dump", "describe_program",
           "flight_path_for", "find_flight_dumps"]

_LOCK = threading.RLock()
_STATE = {"log": None, "resolved": False}

#: set by telemetry.tracing at import: a zero-arg callable returning
#: the thread's current TraceContext (or None).  Kept as a module slot
#: instead of an import so runlog stays import-cycle-free; when absent
#: records are simply unstamped.
_TRACE_GETTER = None

#: fingerprint key -> the compile cause it maps to when it changes
_CAUSE_OF = {"shape": "shape", "dtype": "dtype", "train": "train_mode",
             "autotune": "autotune_winner", "hyper": "hyper_params",
             "sharding": "sharding"}

#: fixed Chrome-trace tid for the telemetry lane (op events use the
#: real thread ids, which are large — a small constant sorts first)
_TRACE_TID = 7


def _jsonable(v):
    """Coerce numpy/jax scalars and tuples so json.dumps never throws
    on a telemetry record (a logging layer must not kill the run)."""
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:
            pass
    return str(v)


def flight_path_for(runlog_path, pid=None):
    """The flight-recorder dump path for a run log.  Pid-suffixed
    (round 20): two processes armed with the same ``MXNET_RUNLOG``
    path (supervisor + relaunched child, router + replica pointed at
    one file) used to clobber each other's post-mortems."""
    return f"{runlog_path}.flight.{os.getpid() if pid is None else pid}.json"


def find_flight_dumps(runlog_path):
    """Every flight dump paired with a run log, newest first — the
    pid-suffixed round-20 names plus the legacy unsuffixed
    ``<runlog>.flight.json`` (pre-round-20 artifacts must stay
    loadable).  Loaders glob through here instead of deriving one
    path, because the dump they want may belong to a DEAD child pid."""
    import glob as _glob

    found = _glob.glob(f"{runlog_path}.flight.*.json")
    legacy = f"{runlog_path}.flight.json"
    if os.path.exists(legacy):
        found.append(legacy)
    found.sort(key=lambda p: (os.path.getmtime(p), p), reverse=True)
    return found


def compile_fingerprint(shape, dtype, train, winners=None, hyper=None,
                        sharding="none"):
    """The canonical compile-event fingerprint.  All three program
    builders (``make_train_step``, ``Executor``, gluon ``CachedOp``)
    build theirs through here, so the keys :data:`_CAUSE_OF` diffs
    into retrace causes can never drift between them."""
    fp = {"shape": str(shape), "dtype": str(dtype),
          "train": bool(train),
          "autotune": {k: v for k, v in (winners or {}).items()
                       if v is not None},
          "sharding": sharding}
    if hyper is not None:
        fp["hyper"] = hyper
    return fp


class RunLog:
    """One run's telemetry sink (see module docstring)."""

    def __init__(self, path, sample=None, flight_depth=None,
                 textfile=None):
        from ..config import get_env

        self.path = os.fspath(path)
        self.sample = max(1, int(sample if sample is not None
                                 else get_env("MXNET_TELEMETRY_SAMPLE")))
        depth = int(flight_depth if flight_depth is not None
                    else get_env("MXNET_FLIGHTREC_DEPTH"))
        self.flight_depth = depth
        self.textfile = textfile if textfile is not None \
            else (get_env("MXNET_METRICS_TEXTFILE") or None)
        self._t0 = time.perf_counter()
        self._lock = threading.RLock()
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a", buffering=1 << 16)
        self._pending = []
        self._ring = collections.deque(maxlen=depth) if depth > 0 \
            else None
        self.counters = {"steps": 0, "bad_steps": 0, "ps_retries": 0,
                         "faults": 0, "compiles": 0, "checkpoints": 0,
                         "h2d_bytes": 0, "feed_wait_s": 0.0,
                         "preempt_signals": 0, "watchdog_stalls": 0,
                         "ckpt_fallbacks": 0, "reshards": 0,
                         "dist_init_retries": 0, "serve_requests": 0,
                         "serve_shed": 0, "serve_batches": 0,
                         "serve_breaker_trips": 0,
                         "serve_tokens_total": 0,
                         "kv_evictions_total": 0,
                         "fleet_requests": 0, "fleet_shed": 0,
                         "fleet_failovers": 0, "fleet_resizes": 0,
                         "fleet_swaps": 0, "fleet_swap_rollbacks": 0,
                         "peer_deaths": 0,
                         "auto_reshards": 0, "ckpt_async_writes": 0,
                         "ckpt_async_errors": 0,
                         "emergency_ckpts": 0, "heal_relaunches": 0,
                         "data_records_skipped": 0,
                         "io_worker_respawns": 0, "io_resyncs": 0,
                         "online_exports": 0, "online_swaps": 0,
                         "online_swaps_shed": 0,
                         "online_relaunches": 0,
                         "freshness_violations": 0}
        self._gauges = {}       # name -> last value (textfile rows)
        self._fps = {}          # program -> last compile fingerprint
        self._programs = {}     # program -> last program_report body
        self._last_program = None
        self._ctx = {"sharding": "none"}
        self._recent = collections.deque(maxlen=64)  # (t, samples)
        self._last = {"loss": None, "samples_per_sec": None}
        self._closed = False
        start = {"type": "run_start", "time": time.time(),
                 "pid": os.getpid(), "parent_pid": os.getppid(),
                 "env": self._env_snapshot(),
                 "config": {"sample": self.sample,
                            "flight_depth": depth,
                            "textfile": self.textfile},
                 "jax": self._jax_snapshot()}
        # round-20 process identity: the spawner (fleet, online loop,
        # healing supervisor) stamps who this process IS, so tracemerge
        # can label its track group without guessing from the filename
        role = os.environ.get("MXNET_PROCESS_ROLE")
        if role:
            start["role"] = str(role)
        rank = os.environ.get("MXNET_PROCESS_RANK")
        if rank is not None:
            try:
                start["rank"] = int(rank)
            except ValueError:
                pass
        self._write(start)

    # ------------------------------------------------------- plumbing
    @staticmethod
    def _env_snapshot():
        return {k: v for k, v in os.environ.items()
                if k.startswith(("MXNET_", "DMLC_", "JAX_", "XLA_"))}

    @staticmethod
    def _jax_snapshot():
        try:
            import jax

            devs = jax.devices()
            return {"version": jax.__version__,
                    "platform": devs[0].platform, "devices": len(devs)}
        except Exception:
            return {}

    def _now(self):
        return time.perf_counter() - self._t0

    def _write(self, rec, flush=True, raw=False):
        """Emit one record.  ``flush=False`` (unsampled steps) only
        queues the dict — serialization and IO are paid in batch at the
        next flushing record, keeping the hot path syscall-free.
        ``raw=True`` skips the ``_jsonable`` recursion for records
        built from known scalars (``default=str`` catches strays)."""
        # round 20: stamp the thread's trace context (when one is
        # bound and the record isn't already stamped) so EVERY record
        # type can join the cross-process timeline.  One TLS read on
        # an armed log; unarmed runs never reach _write at all.
        g = _TRACE_GETTER
        if g is not None and "trace_id" not in rec:
            ctx = g()
            if ctx is not None:
                rec["trace_id"] = ctx.trace_id
                rec["span_id"] = ctx.span_id
                if ctx.parent_span_id is not None:
                    rec["parent_span_id"] = ctx.parent_span_id
        if not raw:
            rec = _jsonable(rec)
        with self._lock:
            if self._closed:
                return
            if not flush:
                self._pending.append(rec)
                return
            try:
                if self._pending:
                    self._f.write("".join(
                        json.dumps(p, default=str) + "\n"
                        for p in self._pending))
                self._f.write(json.dumps(rec, default=str) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                pass  # a full disk must not kill the training run
            finally:
                self._pending.clear()

    def set_context(self, **ctx):
        """Sticky fields stamped onto every later step record (e.g.
        ``sharding='ps'`` from ``Module.init_optimizer``)."""
        self._ctx.update(ctx)

    def should_sync(self, step_no):
        """Whether this step is a sampled one (the caller pays one
        device sync to read loss/metrics)."""
        return step_no % self.sample == 0

    # ----------------------------------------------------------- step
    def step(self, epoch, batch, wall_s, samples, step_no=None,
             loss=None, synced=False, feed_wait_s=None, h2d_bytes=None,
             bad_step=False, sharding=None):
        """Record one training step.  ``feed_wait_s``/``h2d_bytes`` are
        DELTAS for this step (the fit session computes them from
        ``DeviceFeedIter.stats()`` snapshots)."""
        c = self.counters
        step_no = c["steps"] if step_no is None else int(step_no)
        c["steps"] += 1
        if bad_step:
            c["bad_steps"] += 1
        if feed_wait_s:
            c["feed_wait_s"] += float(feed_wait_s)
        if h2d_bytes:
            c["h2d_bytes"] += int(h2d_bytes)
        sps = (float(samples) / wall_s) if wall_s > 0 else None
        # collective accounting comes from the program driving these
        # steps: an explicit set_context(program=...) pin wins, else
        # the most recently traced program (the one a fit loop just
        # compiled), never an arbitrary stale report
        prog = self._programs.get(
            self._ctx.get("program") or self._last_program)
        coll = prog.get("collectives") if prog else None
        t = self._now()
        rec = {
            "type": "step", "t": round(t, 6), "epoch": int(epoch),
            "step": step_no, "batch": int(batch),
            "wall_ms": round(wall_s * 1e3, 4), "samples": int(samples),
            "samples_per_sec": round(sps, 3) if sps else None,
            "loss": float(loss) if loss is not None else None,
            "synced": bool(synced),
            "feed_wait_ms": round(feed_wait_s * 1e3, 4)
            if feed_wait_s is not None else None,
            "h2d_bytes": int(h2d_bytes) if h2d_bytes is not None
            else None,
            "collective_counts": dict(coll["counts"]) if coll else None,
            "collective_bytes": int(coll["total_bytes"]) if coll
            else None,
            "sharding": sharding if sharding is not None
            else self._ctx.get("sharding", "none"),
            "bad_step": bool(bad_step),
            "ps_retries": c["ps_retries"], "faults": c["faults"],
            "checkpoints": c["checkpoints"],
        }
        # hot path: the record is built from known scalars, so skip the
        # _jsonable recursion and only pay the flush syscall on sampled
        # steps (default=str catches any stray numpy scalar)
        self._write(rec, flush=synced, raw=True)
        if self._ring is not None:
            self._ring.append(rec)
        self._recent.append((t, samples))
        if loss is not None:
            self._last["loss"] = float(loss)
        if sps:
            self._last["samples_per_sec"] = sps
        self._trace_step(t, wall_s, feed_wait_s, sps, loss)
        if synced and self.textfile:
            self.write_textfile()
        return rec

    def _trace_step(self, t_end, wall_s, feed_wait_s, sps, loss):
        """Mirror the step onto the profiler's Chrome-trace timeline
        (one telemetry lane next to the op events)."""
        from .. import profiler

        if not profiler.is_running():
            return
        self._trace_meta()
        start = profiler.now_us() - wall_s * 1e6
        if feed_wait_s:
            profiler.record_span("feed_wait", "telemetry",
                                 start, feed_wait_s * 1e6,
                                 tid=_TRACE_TID)
        profiler.record_span(f"step {self.counters['steps'] - 1}",
                             "telemetry", start, wall_s * 1e6,
                             tid=_TRACE_TID)
        if sps:
            profiler.record_counter("throughput", round(sps, 2),
                                    cat="telemetry", tid=_TRACE_TID)
        if loss is not None:
            profiler.record_counter("loss", float(loss),
                                    cat="telemetry", tid=_TRACE_TID)

    def _trace_meta(self):
        from .. import profiler

        # once per profiler run WINDOW, not once per RunLog: a dump
        # (finished=True) drains the buffer, so the next window needs
        # its lane-name metadata re-emitted
        gen = profiler.run_generation()
        if getattr(self, "_trace_named_gen", None) != gen:
            profiler.record_meta("thread_name", {"name": "telemetry"},
                                 tid=_TRACE_TID)
            self._trace_named_gen = gen

    def recent_throughput(self, since=None):
        """samples/sec over the recent step window (the authoritative
        rate ``callback.Speedometer`` reads when telemetry is live).
        ``since`` (a ``time.perf_counter()`` stamp) restricts the
        window to steps recorded after it, so a reporting interval
        that opened mid-run (Speedometer's tic) is not diluted by an
        eval pass or the previous epoch's steps."""
        recent = list(self._recent)
        if since is not None:
            cut = since - self._t0
            recent = [(t, s) for t, s in recent if t >= cut]
        if len(recent) < 2:
            return None
        (t0, _), (t1, _) = recent[0], recent[-1]
        if t1 <= t0:
            return None
        n = sum(s for _, s in recent[1:])
        return n / (t1 - t0)

    # -------------------------------------------------- compile events
    def compile_event(self, program, fingerprint, cache="miss",
                      causes=None):
        """Record a program (re)trace.  ``fingerprint`` keys are diffed
        against the program's last one to derive the retrace causes:
        shape / dtype / train_mode / autotune_winner / hyper_params /
        sharding; the first trace of a program is ``first_trace``."""
        fingerprint = _jsonable(fingerprint)
        with self._lock:
            prev = self._fps.get(program)
            if causes is None:
                if prev is None:
                    causes = ["first_trace"]
                else:
                    keys = set(prev) | set(fingerprint)
                    causes = sorted(
                        {_CAUSE_OF.get(k, "program") for k in keys
                         if prev.get(k) != fingerprint.get(k)})
                    causes = causes or ["program"]
            self._fps[program] = fingerprint
            self.counters["compiles"] += 1
        rec = {"type": "compile", "t": round(self._now(), 6),
               "program": program, "cache": cache,
               "causes": list(causes), "fingerprint": fingerprint}
        self._write(rec)
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_instant(f"compile:{program}", "telemetry",
                                    args={"causes": list(causes)},
                                    tid=_TRACE_TID)
        return rec

    # ------------------------------------------------- program reports
    def program_report(self, program, memory=None, flops=None,
                       bytes_accessed=None, collectives=None,
                       extra=None):
        body = {"memory": memory or {}, "flops": float(flops or 0.0),
                "bytes_accessed": float(bytes_accessed or 0.0),
                "collectives": collectives}
        if extra:
            body.update(extra)
        with self._lock:
            self._programs[program] = body
            self._last_program = program
        self._write({"type": "program_report",
                     "t": round(self._now(), 6), "program": program,
                     **body})
        return body

    # ------------------------------------------------------ checkpoint
    def checkpoint_event(self, prefix, version, duration_s, nbytes,
                         **extra):
        """One checkpoint write (or recovery — ``reason='fallback'``
        with the skipped bad versions rides in ``extra``).  A fallback
        is a recovery READ: it counts only ``ckpt_fallbacks`` (bumped
        by the caller), never the ``checkpoints`` write counter the
        step records carry."""
        if extra.get("reason") != "fallback":
            self.counters["checkpoints"] += 1
        self._write({"type": "checkpoint", "t": round(self._now(), 6),
                     "prefix": str(prefix), "version": int(version),
                     "duration_s": round(float(duration_s), 6),
                     "bytes": int(nbytes), **_jsonable(extra)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_span(
                "checkpoint", "telemetry",
                profiler.now_us() - duration_s * 1e6, duration_s * 1e6,
                args={"version": int(version), "bytes": int(nbytes)},
                tid=_TRACE_TID)

    # ------------------------------------------------ watchdog / stats
    def watchdog(self, phase, quiet_s, stack_path):
        """One hang-watchdog stall: the heartbeat went quiet for
        ``quiet_s`` during ``phase`` and an all-thread stack dump
        landed at ``stack_path`` (telemetry.watchdog fires this from
        its own thread — the stalled main thread cannot)."""
        self._write({"type": "watchdog", "t": round(self._now(), 6),
                     "phase": str(phase),
                     "quiet_s": round(float(quiet_s), 3),
                     "stack_path": str(stack_path)
                     if stack_path else None})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_instant(
                "watchdog_stall", "telemetry",
                args={"phase": str(phase),
                      "quiet_s": round(float(quiet_s), 3)},
                tid=_TRACE_TID)

    def span(self, name, t0, t1, *, trace_id, span_id,
             parent_span_id=None, kind="internal", flush=True, **attrs):
        """One completed distributed-trace span (telemetry.tracing).
        ``t0``/``t1`` are ``time.perf_counter()`` readings; the record
        stores the run-relative END time plus ``dur_ms`` so
        tools/tracemerge.py reconstructs wall time from
        ``run_start.time``.  Hot emitters (the serve dispatch loop)
        pass ``flush=False`` — the spans queue behind the flushing
        ``serve`` record of the same batch, adding zero syscalls."""
        rec = {"type": "span", "t": round(t1 - self._t0, 6),
               "name": str(name), "kind": str(kind),
               "dur_ms": round((t1 - t0) * 1e3, 4),
               "trace_id": trace_id, "span_id": span_id,
               "parent_span_id": parent_span_id}
        if attrs:
            rec["attrs"] = _jsonable(attrs)
        self._write(rec, flush=flush, raw=True)
        return rec

    def serve(self, *, model, batch, padded_to, queue_depth,
              latency_ms, deadline_margin_ms=None, shed=0,
              breaker="closed"):
        """One dispatched serving microbatch (serving.ModelServer):
        live request count vs the bucketed padded shape, dispatch
        latency, queue depth left behind, the cumulative shed count
        and the breaker state — the per-batch row an SLO dashboard
        folds into p99s."""
        dur_s = float(latency_ms) / 1e3
        self._write({"type": "serve", "t": round(self._now(), 6),
                     "model": str(model), "batch": int(batch),
                     "padded_to": int(padded_to),
                     "queue_depth": int(queue_depth),
                     "latency_ms": round(float(latency_ms), 4),
                     "deadline_margin_ms":
                     round(float(deadline_margin_ms), 4)
                     if deadline_margin_ms is not None else None,
                     "shed": int(shed), "breaker": str(breaker)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_span(
                "serve_batch", "telemetry",
                profiler.now_us() - dur_s * 1e6, dur_s * 1e6,
                args={"batch": int(batch), "padded_to": int(padded_to),
                      "queue_depth": int(queue_depth)},
                tid=_TRACE_TID)
            profiler.record_counter("serve_queue_depth",
                                    int(queue_depth),
                                    cat="telemetry", tid=_TRACE_TID)

    def generate(self, *, name, tokens, tokens_s, ttft_p50_ms,
                 ttft_p99_ms, in_flight, max_in_flight, evictions,
                 shed, pages_in_use, queue_depth, kv_dtype, compiles):
        """One generative-serving snapshot
        (serving.generate.GenerativeServer.report): decode throughput,
        time-to-first-token percentiles, continuous-batching occupancy,
        paged-KV pool pressure and the cumulative eviction/shed
        counters — plus the post-warm compile count whose expected
        value under continuous batching is exactly zero."""
        self._write({"type": "generate", "t": round(self._now(), 6),
                     "name": str(name), "tokens": int(tokens),
                     "tokens_s": round(float(tokens_s), 4),
                     "ttft_p50_ms": round(float(ttft_p50_ms), 4)
                     if ttft_p50_ms is not None else None,
                     "ttft_p99_ms": round(float(ttft_p99_ms), 4)
                     if ttft_p99_ms is not None else None,
                     "in_flight": int(in_flight),
                     "max_in_flight": int(max_in_flight),
                     "evictions": int(evictions), "shed": int(shed),
                     "pages_in_use": int(pages_in_use),
                     "queue_depth": int(queue_depth),
                     "kv_dtype": str(kv_dtype),
                     "compiles": int(compiles)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_counter("serve_tokens_total", int(tokens),
                                    cat="telemetry", tid=_TRACE_TID)
            profiler.record_counter("kv_pages_in_use",
                                    int(pages_in_use),
                                    cat="telemetry", tid=_TRACE_TID)

    def fleet(self, *, action, replicas, ready, queue_depth,
              queue_ewma, requests, failovers, shed):
        """One fleet-router observation (serving.fleet.FleetRouter):
        the replica set's live/ready counts, the summed queue depth
        and its autoscaling EWMA, and the router's cumulative
        request/failover/shed counters — stamped with the ``action``
        (probe / eject / resize / swap / close) that produced it."""
        self._write({"type": "fleet", "t": round(self._now(), 6),
                     "action": str(action), "replicas": int(replicas),
                     "ready": int(ready),
                     "queue_depth": int(queue_depth),
                     "queue_ewma": round(float(queue_ewma), 4),
                     "requests": int(requests),
                     "failovers": int(failovers), "shed": int(shed)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_counter("fleet_queue_ewma",
                                    round(float(queue_ewma), 3),
                                    cat="telemetry", tid=_TRACE_TID)

    def heal(self, action, **fields):
        """One self-healing runtime observation (resilience.healing):
        a declared peer death, an abandoned collective, an emergency
        checkpoint flush, the survivor's heal_exit, a supervisor
        relaunch or the healed resume — stamped with the process's
        cumulative healing counters so a single record tells the
        whole story so far."""
        c = self.counters
        self._write({"type": "heal", "t": round(self._now(), 6),
                     "action": str(action),
                     "peer_deaths": int(c.get("peer_deaths", 0)),
                     "emergency_ckpts": int(c.get("emergency_ckpts",
                                                  0)),
                     "heal_relaunches": int(c.get("heal_relaunches",
                                                  0)),
                     "auto_reshards": int(c.get("auto_reshards", 0)),
                     **_jsonable(fields)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_instant(
                f"heal:{action}", "telemetry",
                args=_jsonable(fields), tid=_TRACE_TID)

    def data_plane(self, action, *, workers=0, **fields):
        """One data-plane observation (io.ImageRecordIter and friends):
        a quarantined record, a worker-pool respawn or an epoch
        summary — stamped with the process's cumulative
        records-skipped / worker-respawn counters so a single record
        tells how shrunken the fed stream is so far."""
        c = self.counters
        self._write({"type": "data", "t": round(self._now(), 6),
                     "action": str(action), "workers": int(workers),
                     "skipped": int(c.get("data_records_skipped", 0)),
                     "respawns": int(c.get("io_worker_respawns", 0)),
                     **_jsonable(fields)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_instant(
                f"data:{action}", "telemetry",
                args=_jsonable(fields), tid=_TRACE_TID)

    def quantize(self, action, *, mode="", layers=0, excluded=0,
                 **fields):
        """One quantized-inference pipeline observation
        (mxnet_tpu.quantization): a calibration pass, a net rewrite,
        an adoption race or an export — which mode ran and how many
        layers it touched."""
        self._write({"type": "quantize", "t": round(self._now(), 6),
                     "action": str(action), "mode": str(mode),
                     "layers": int(layers), "excluded": int(excluded),
                     **_jsonable(fields)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_instant(
                f"quantize:{action}", "telemetry",
                args=_jsonable(fields), tid=_TRACE_TID)

    def freshness(self, action, *, version=0, freshness_ms=None,
                  **fields):
        """One online-learning loop observation (mxnet_tpu.online): a
        trainer export published, a rolling swap committed / shed /
        rolled back, a freshness-SLO violation or a supervisor
        relaunch — stamped with the artifact's monotonic model
        version, the measured sample-to-served latency and the loop's
        cumulative counters, so the run log alone proves version
        monotonicity and names every shed swap."""
        c = self.counters
        self._write({"type": "freshness", "t": round(self._now(), 6),
                     "action": str(action), "version": int(version),
                     "freshness_ms": (round(float(freshness_ms), 3)
                                      if freshness_ms is not None
                                      else None),
                     "exports": int(c.get("online_exports", 0)),
                     "swaps": int(c.get("online_swaps", 0)),
                     "swaps_shed": int(c.get("online_swaps_shed", 0)),
                     "violations": int(c.get("freshness_violations",
                                             0)),
                     "relaunches": int(c.get("online_relaunches", 0)),
                     **_jsonable(fields)})
        from .. import profiler

        if profiler.is_running():
            self._trace_meta()
            profiler.record_instant(
                f"freshness:{action}", "telemetry",
                args=_jsonable(fields), tid=_TRACE_TID)

    def opstats(self, rows, source="profiler"):
        """The aggregate per-op table (telemetry.opstats) as one
        ``program_report``-style record."""
        self._write({"type": "opstats", "t": round(self._now(), 6),
                     "source": str(source), "ops": len(rows),
                     "rows": rows})

    def tensor_stats(self, step, tensors, where="grad",
                     nonfinite=False, epoch=None):
        """One sampled numerics-monitor snapshot: per-tensor summary
        rows (l2/min/max/nan/inf/zero_frac) for named activations or
        gradients — the record that EXPLAINS a NaN step."""
        self._write({"type": "tensor_stats",
                     "t": round(self._now(), 6),
                     "step": int(step),
                     "epoch": int(epoch) if epoch is not None else None,
                     "where": str(where),
                     "nonfinite": bool(nonfinite),
                     "tensors": tensors})

    # ---------------------------------------------------------- events
    def event(self, kind, **fields):
        self._write({"type": "event", "t": round(self._now(), 6),
                     "kind": kind, **fields})

    def count(self, counter, delta=1):
        with self._lock:
            self.counters[counter] = \
                self.counters.get(counter, 0) + delta

    def gauge(self, name, value):
        """Set a point-in-time gauge (readiness/liveness, residency
        bytes...).  Gauges land in the Prometheus textfile next to the
        counters; a CHANGED value rewrites the textfile immediately so
        probes and scrapers read the same truth as the in-process
        health() that set it (state flips are rare — steady-state
        health polling costs one dict compare)."""
        value = float(value)
        with self._lock:
            changed = self._gauges.get(name) != value
            self._gauges[name] = value
        if changed and self.textfile:
            self.write_textfile()

    # -------------------------------------------------- flight recorder
    @property
    def flight_path(self):
        return flight_path_for(self.path)

    def flight_dump(self, reason):
        """Atomically write the flight-recorder snapshot: the last
        ``flight_depth`` step records plus config/env/compile
        fingerprints and counters.  Safe to call from crash paths (the
        fault-injection point is disabled so a ``ckpt.write`` fault
        spec cannot tear the post-mortem of its own crash)."""
        if self._ring is None:
            return None
        from ..resilience.checkpoint import atomic_write_bytes

        payload = _jsonable({
            "reason": reason, "time": time.time(), "pid": os.getpid(),
            "depth": self._ring.maxlen, "counters": dict(self.counters),
            "context": dict(self._ctx), "env": self._env_snapshot(),
            "programs": dict(self._fps),
            "program_reports": dict(self._programs),
            "steps": list(self._ring),
        })
        try:
            atomic_write_bytes(
                self.flight_path,
                json.dumps(payload, indent=1).encode(),
                inject_point=None)
        except OSError:
            return None
        self.event("flight_dump", reason=reason, path=self.flight_path)
        return self.flight_path

    # ------------------------------------------------ metrics textfile
    def write_textfile(self):
        """Prometheus-textfile export (node_exporter textfile collector
        convention), atomically rewritten so a scraper never reads a
        torn file."""
        if not self.textfile:
            return None
        from ..resilience.checkpoint import atomic_write_bytes

        lines = []
        for k, v in sorted(self.counters.items()):
            kind = "counter" if isinstance(v, int) else "gauge"
            lines.append(f"# TYPE mxnet_tpu_{k} {kind}")
            lines.append(f"mxnet_tpu_{k} {v}")
        # Prometheus-convention *_total counter aliases for the rates
        # dashboards actually graph: retraces (compile events), feed
        # wait seconds, and watchdog stalls
        for name, v in (("retrace_total", self.counters["compiles"]),
                        ("feed_wait_seconds_total",
                         self.counters["feed_wait_s"]),
                        ("watchdog_stalls_total",
                         self.counters["watchdog_stalls"])):
            lines.append(f"# TYPE mxnet_tpu_{name} counter")
            lines.append(f"mxnet_tpu_{name} {v}")
        for k, v in sorted(self._last.items()):
            if v is None:
                continue
            lines.append(f"# TYPE mxnet_tpu_{k} gauge")
            lines.append(f"mxnet_tpu_{k} {v}")
        # point-in-time gauges (serve_ready/serve_live readiness and
        # liveness rows the fleet's health probes also read).  Names
        # may carry Prometheus labels ('serve_ready{model="m"}') —
        # the TYPE line names the metric FAMILY, once
        with self._lock:
            gauges = dict(self._gauges)
        typed = set()
        for k, v in sorted(gauges.items()):
            family = k.split("{", 1)[0]
            if family not in typed:
                typed.add(family)
                lines.append(f"# TYPE mxnet_tpu_{family} gauge")
            lines.append(f"mxnet_tpu_{k} "
                         f"{int(v) if v == int(v) else v}")
        try:
            atomic_write_bytes(self.textfile,
                               ("\n".join(lines) + "\n").encode(),
                               inject_point=None)
        except OSError:
            return None
        return self.textfile

    # ------------------------------------------------------------ close
    def close(self):
        with self._lock:
            if self._closed:
                return
            self._write({"type": "run_end", "t": round(self._now(), 6),
                         "counters": dict(self.counters)})
            if self.textfile:
                self.write_textfile()
            self._closed = True
            try:
                self._f.close()
            except OSError:
                pass


# -------------------------------------------------- module-level state
def current():
    """The active RunLog, or None.  The no-op fast exit: two dict
    lookups when ``MXNET_RUNLOG`` is unset."""
    if not _STATE["resolved"]:
        _resolve()
    return _STATE["log"]


def _resolve():
    with _LOCK:
        if _STATE["resolved"]:
            return
        from ..config import get_env

        path = get_env("MXNET_RUNLOG")
        log = None
        if path:
            try:
                log = RunLog(path)
            except Exception as e:  # noqa: BLE001 — logging layer
                # an unwritable run-log path or a bad telemetry knob
                # (MXNET_TELEMETRY_SAMPLE=twenty) disables telemetry
                # with a warning — it must not kill every forward/
                # step/fit that touches a wire point
                import warnings

                warnings.warn(f"MXNET_RUNLOG={path!r} unusable ({e}); "
                              "telemetry disabled", stacklevel=3)
        _STATE["log"] = log
        _STATE["resolved"] = True


def reset(path=None):
    """Close any active log and re-resolve — from ``path`` when given,
    else from ``MXNET_RUNLOG`` (tests and bench arm telemetry at a
    precise point rather than at import)."""
    with _LOCK:
        if _STATE["log"] is not None:
            _STATE["log"].close()
        _STATE["log"] = None
        _STATE["resolved"] = False
        if path is not None:
            _STATE["log"] = RunLog(path)
            _STATE["resolved"] = True
    return _STATE["log"]


def close():
    with _LOCK:
        if _STATE["log"] is not None:
            _STATE["log"].close()
        _STATE["log"] = None
        _STATE["resolved"] = False


# --------------------------------------- convenience no-op-safe wrappers
def compile_event(program, fingerprint, cache="miss", causes=None):
    rl = current()
    if rl is not None:
        rl.compile_event(program, fingerprint, cache=cache,
                         causes=causes)


def event(kind, **fields):
    rl = current()
    if rl is not None:
        rl.event(kind, **fields)


def count(counter, delta=1):
    rl = current()
    if rl is not None:
        rl.count(counter, delta)


def gauge(name, value):
    rl = current()
    if rl is not None:
        rl.gauge(name, value)


def heal(action, **fields):
    rl = current()
    if rl is not None:
        rl.heal(action, **fields)


def data_plane(action, *, workers=0, **fields):
    rl = current()
    if rl is not None:
        rl.data_plane(action, workers=workers, **fields)


def quantize(action, *, mode="", layers=0, excluded=0, **fields):
    rl = current()
    if rl is not None:
        rl.quantize(action, mode=mode, layers=layers,
                    excluded=excluded, **fields)


def generate(**fields):
    rl = current()
    if rl is not None:
        rl.generate(**fields)


def freshness(action, *, version=0, freshness_ms=None, **fields):
    rl = current()
    if rl is not None:
        rl.freshness(action, version=version,
                     freshness_ms=freshness_ms, **fields)


def checkpoint_event(prefix, version, duration_s, nbytes, **extra):
    rl = current()
    if rl is not None:
        rl.checkpoint_event(prefix, version, duration_s, nbytes,
                            **extra)


def program_report(program, **kw):
    rl = current()
    if rl is not None:
        rl.program_report(program, **kw)


def flight_dump(reason):
    rl = current()
    if rl is not None:
        return rl.flight_dump(reason)
    return None


# --------------------------------------------- program introspection
def describe_program(fn_or_compiled, *args, program="program",
                     record=True, **kwargs):
    """Compile/memory introspection of one XLA program — the
    ``profile_memory`` analog XLA actually exposes.

    ``fn_or_compiled`` may be a jitted callable (lowered+compiled here
    with ``*args``; the persistent compilation cache makes a re-compile
    of an already-seen program a disk read), a ``Lowered``, or a
    ``Compiled``.  Returns a dict with ``memory`` (argument/output/
    temp/alias/generated-code bytes from ``compiled.memory_analysis()``),
    ``flops``/``bytes_accessed`` (``cost_analysis()``) and
    ``collectives`` (HLO collective counts/bytes via
    ``parallel.zero.collective_bytes``); records a ``program_report``
    into the active RunLog when ``record`` is True.
    """
    compiled = fn_or_compiled
    if hasattr(compiled, "lower"):
        compiled = compiled.lower(*args, **kwargs)
    if hasattr(compiled, "compile"):
        compiled = compiled.compile()

    memory = {}
    try:
        ma = compiled.memory_analysis()
        for field in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes"):
            v = getattr(ma, field, None)
            if v is not None:
                memory[field.replace("_size_in_bytes", "_bytes")] = \
                    int(v)
    except Exception:
        pass  # backend without memory stats: report what we can
    flops = bytes_accessed = 0.0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        bytes_accessed = float(ca.get("bytes accessed", 0.0))
    except Exception:
        pass
    collectives = None
    try:
        from ..parallel.zero import collective_bytes

        collectives = collective_bytes(compiled.as_text())
    except Exception:
        pass
    report = {"program": program, "memory": memory, "flops": flops,
              "bytes_accessed": bytes_accessed,
              "collectives": collectives}
    if record:
        rl = current()
        if rl is not None:
            rl.program_report(program, memory=memory, flops=flops,
                              bytes_accessed=bytes_accessed,
                              collectives=collectives)
    return report
