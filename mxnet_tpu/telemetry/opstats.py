"""Aggregate per-op statistics folded from the profiler's event buffer.

The reference's ``profiler.dumps()`` is backed by AggregateStats
(src/profiler/aggregate_stats.cc): every op event lands in a per-op
row of count/total/min/max/avg dispatch time, dumped as a sorted text
table.  Our profiler keeps the richer artifact — the full Chrome-trace
event list — so this module derives the aggregate FROM the events,
which buys the two columns the reference table lacks:

* **p99_us** — tail latency per op, computed from the complete sample
  set rather than a running min/max pair;
* **bytes** — summed where the dispatcher knew the output size (the
  ``bytes`` arg on an op event).

Three outputs, same data:

* :func:`aggregate` — programmatic: ``{name: row_dict}``;
* :func:`dumps` — the ``profiler.dumps()``-style text table (or JSON);
* :func:`record` — a ``program_report``-style ``opstats`` record into
  the active RunLog, so the bench's run log carries the op table next
  to the step records that paid for it.
"""
from __future__ import annotations

import json as _json
import math

__all__ = ["aggregate", "dumps", "record", "percentile", "SORT_KEYS"]

SORT_KEYS = ("total", "avg", "min", "max", "p99", "count", "bytes")


def percentile(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (q in [0, 1]) —
    shared with benchmark/opperf.py's p50/p99 columns so the two rank
    conventions cannot drift."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return float(sorted_vals[rank - 1])


def aggregate(events=None, cat="operator"):
    """Fold complete-span ('X') trace events into per-op rows.

    ``events`` defaults to a snapshot of the profiler's live buffer;
    ``cat`` filters by event category (``"operator"`` = the nd
    dispatcher's op events; pass None to aggregate every span, e.g.
    the telemetry lane's step/feed_wait spans).  Returns
    ``{name: {count, total_us, min_us, max_us, avg_us, p99_us,
    bytes}}`` — ``bytes`` is None when no event carried one.
    """
    if events is None:
        from .. import profiler

        events = profiler.events_snapshot()
    durs = {}
    nbytes = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        if cat is not None and ev.get("cat") != cat:
            continue
        name = ev.get("name")
        durs.setdefault(name, []).append(float(ev.get("dur", 0.0)))
        b = (ev.get("args") or {}).get("bytes")
        if b is not None:
            nbytes[name] = nbytes.get(name, 0) + int(b)
    rows = {}
    for name, ds in durs.items():
        ds.sort()
        total = sum(ds)
        rows[name] = {
            "count": len(ds),
            "total_us": total,
            "min_us": ds[0],
            "max_us": ds[-1],
            "avg_us": total / len(ds),
            "p99_us": percentile(ds, 0.99),
            "bytes": nbytes.get(name),
        }
    return rows


def dumps(format="table", sort_by="total", ascending=False,
          events=None, cat="operator"):
    """The ``profiler.dumps()`` analog over the event buffer: a sorted
    per-op text table (or JSON) with the p99/bytes columns."""
    from ..base import MXNetError

    if format not in ("table", "json"):
        raise MXNetError(f"invalid format {format!r}")
    if sort_by not in SORT_KEYS:
        raise MXNetError(f"invalid sort_by {sort_by!r} "
                         f"(one of {SORT_KEYS})")
    rows = aggregate(events=events, cat=cat)
    key = {"total": "total_us", "avg": "avg_us", "min": "min_us",
           "max": "max_us", "p99": "p99_us", "count": "count",
           "bytes": "bytes"}[sort_by]
    order = sorted(rows.items(), key=lambda kv: kv[1][key] or 0,
                   reverse=not ascending)
    if format == "json":
        return _json.dumps([{"name": n, **r} for n, r in order])
    lines = [f"{'Name':<40s}{'Calls':>8s}{'Total(us)':>14s}"
             f"{'Min(us)':>12s}{'Max(us)':>12s}{'Avg(us)':>12s}"
             f"{'P99(us)':>12s}{'Bytes':>14s}"]
    for n, r in order:
        b = "-" if r["bytes"] is None else str(r["bytes"])
        lines.append(
            f"{n:<40.40s}{r['count']:>8d}{r['total_us']:>14.1f}"
            f"{r['min_us']:>12.1f}{r['max_us']:>12.1f}"
            f"{r['avg_us']:>12.1f}{r['p99_us']:>12.1f}{b:>14s}")
    return "\n".join(lines)


def record(source="profiler", events=None, cat="operator", top=None):
    """Write the aggregate as an ``opstats`` RunLog record (no-op when
    telemetry is unarmed).  ``top`` keeps only the N largest rows by
    total time so a long eager session cannot bloat the run log.
    Returns the row dict either way (callers fold it into reports)."""
    rows = aggregate(events=events, cat=cat)
    if top is not None and len(rows) > top:
        keep = sorted(rows, key=lambda n: rows[n]["total_us"],
                      reverse=True)[:int(top)]
        rows = {n: rows[n] for n in keep}
    from .runlog import current

    rl = current()
    if rl is not None and rows:
        rl.opstats(rows, source=source)
    return rows
