"""W3C-style distributed trace context: one causal timeline per request.

PR 5/6 telemetry attributes *one process's* time; PRs 10-18 grew the
system into a fleet of processes (FleetRouter + replica subprocesses,
the online trainer -> export -> rolling-swap loop, healing relaunches)
whose runlogs are deliberately disconnected — fleet spawn scrubs
``MXNET_RUNLOG`` and ``runlog_dir`` drops isolated ``replica-N.jsonl``
files.  This module is the cross-process stitch:

* :class:`TraceContext` — ``trace_id`` (32 hex) / ``span_id`` (16 hex)
  / ``parent_span_id``, carried as a W3C ``traceparent`` header
  (``00-<trace_id>-<span_id>-01``) over HTTP and as the
  ``MXNET_TRACE_CONTEXT`` env stamp into spawned subprocesses.
* a per-thread context stack (:func:`use`, :func:`current_context`)
  seeded from the env stamp, so a replica's batch spans parent onto
  the router hop that caused them.
* span emission (:func:`emit_span`, :func:`span`) into the active
  RunLog as ``span`` records — merged across processes by
  ``tools/tracemerge.py`` into a single Perfetto timeline.

Zero-cost contract (the PR-5 bound): with ``MXNET_RUNLOG`` unset,
:func:`enabled` is the runlog ``current()`` fast path (two dict
lookups) and nothing mints ids, touches urandom, or builds dicts.
Trace ids are only generated when telemetry is armed or an inbound
context (header / env stamp) already exists.
"""
from __future__ import annotations

import os
import threading
import time

from . import runlog as _runlog

__all__ = [
    "TraceContext", "TRACEPARENT_HEADER", "TRACE_ENV", "ROLE_ENV",
    "RANK_ENV", "mint", "from_header", "process_context",
    "current_context", "use", "span", "emit_span", "enabled",
    "stamp_env", "new_span_id",
]

#: HTTP header name for the cross-process hop (W3C Trace Context).
TRACEPARENT_HEADER = "traceparent"
#: env stamp set by every spawner (fleet replicas, online trainer,
#: healing relaunch) so the child's root spans parent onto the spawn.
TRACE_ENV = "MXNET_TRACE_CONTEXT"
#: process identity stamps (satellite: run_start role/rank).
ROLE_ENV = "MXNET_PROCESS_ROLE"
RANK_ENV = "MXNET_PROCESS_RANK"

_VERSION = "00"
_FLAGS = "01"


class TraceContext:
    """An immutable (trace_id, span_id, parent_span_id) triple."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id, span_id, parent_span_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    # -------------------------------------------------------- wire
    def to_header(self):
        """``00-<trace_id>-<span_id>-01`` — the value a router sends
        and a frontend echoes back."""
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-{_FLAGS}"

    def child(self):
        """A new context in the same trace, parented on this span."""
        return TraceContext(self.trace_id, _gen_span_id(), self.span_id)

    def __repr__(self):  # pragma: no cover - debug aid
        return (f"TraceContext({self.trace_id[:8]}.., span={self.span_id},"
                f" parent={self.parent_span_id})")


def _gen_trace_id():
    return os.urandom(16).hex()


def _gen_span_id():
    return os.urandom(8).hex()


#: public alias for emitters that build span records by hand (the
#: serve dispatch loop fans one request context into several child
#: spans without allocating intermediate TraceContext objects)
new_span_id = _gen_span_id


def mint():
    """A brand-new root context (fresh trace, no parent)."""
    return TraceContext(_gen_trace_id(), _gen_span_id(), None)


def from_header(value):
    """Parse a ``traceparent`` header (or the env stamp, same format).
    Returns None on anything malformed — an unparseable header must
    degrade to "untraced", never to an exception on the serve path."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) < 3:
        return None
    if len(parts) == 3:          # tolerate a missing flags field
        _, trace_id, span_id = parts
    else:
        trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, None)


# ------------------------------------------------------------ process root
_PROC = {"ctx": None, "resolved": False}
_PROC_LOCK = threading.Lock()


def process_context():
    """The context stamped on this process via ``MXNET_TRACE_CONTEXT``
    (parsed once), or None.  A stamped child's spans parent onto the
    spawner's span id — the cross-process link tracemerge draws."""
    if _PROC["resolved"]:
        return _PROC["ctx"]
    with _PROC_LOCK:
        if not _PROC["resolved"]:
            _PROC["ctx"] = from_header(os.environ.get(TRACE_ENV))
            _PROC["resolved"] = True
    return _PROC["ctx"]


def _reset_process_context():
    """Test hook: re-read ``MXNET_TRACE_CONTEXT`` on next use."""
    with _PROC_LOCK:
        _PROC["ctx"] = None
        _PROC["resolved"] = False


# ------------------------------------------------------------ thread stack
_TLS = threading.local()


def current_context():
    """The innermost bound context on this thread, else the process
    stamp, else None.  Never mints."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        return stack[-1]
    return process_context()


class use:
    """Bind ``ctx`` as the current context on this thread::

        with tracing.use(ctx):
            ...  # spans emitted here parent onto ctx
    """

    __slots__ = ("ctx",)

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        stack.append(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        try:
            _TLS.stack.pop()
        except (AttributeError, IndexError):  # pragma: no cover
            pass
        return False


def enabled():
    """Span emission is armed iff a RunLog is — the same two-dict-
    lookup fast path as every other telemetry wrapper."""
    return _runlog.current() is not None


# ---------------------------------------------------------------- emission
def emit_span(name, t0, t1, ctx, kind="internal", parent_span_id=None,
              flush=True, **attrs):
    """Write one completed span into the active RunLog.

    ``t0``/``t1`` are ``time.perf_counter()`` readings (the runlog's
    native clock); the record stores run-relative end time + duration
    so tracemerge can reconstruct wall time via ``run_start.time``.
    ``parent_span_id`` overrides ``ctx.parent_span_id`` (e.g. chaining
    queue -> coalesce -> compute as siblings under one request span).
    No-op when telemetry is unarmed."""
    rl = _runlog.current()
    if rl is None:
        return None
    parent = ctx.parent_span_id if parent_span_id is None else parent_span_id
    rl.span(name, t0, t1, trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_span_id=parent, kind=kind, flush=flush, **attrs)
    return ctx


class span:
    """Context manager: time a block and emit it as a child span of the
    current context.  When telemetry is unarmed this binds nothing and
    emits nothing (one ``current()`` check on enter)::

        with tracing.span("export", model_version=3) as ctx:
            ...
    """

    __slots__ = ("name", "kind", "attrs", "ctx", "_t0", "_use")

    def __init__(self, name, kind="internal", ctx=None, **attrs):
        self.name = name
        self.kind = kind
        self.attrs = attrs
        self.ctx = ctx
        self._t0 = None
        self._use = None

    def __enter__(self):
        if self.ctx is None:
            if not enabled():
                return None
            parent = current_context()
            self.ctx = parent.child() if parent is not None else mint()
        self._use = use(self.ctx)
        self._use.__enter__()
        self._t0 = time.perf_counter()
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        if self._use is None:
            return False
        t1 = time.perf_counter()
        self._use.__exit__()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        emit_span(self.name, self._t0, t1, self.ctx, kind=self.kind,
                  **self.attrs)
        return False


# ------------------------------------------------------------------ spawn
def stamp_env(env, role, rank=None, ctx=None):
    """Stamp a subprocess environment with trace + identity: sets
    ``MXNET_TRACE_CONTEXT`` to a child of ``ctx`` (default: the
    current context; minted fresh when telemetry is armed and no
    context exists — so a traced parent always links its children) and
    ``MXNET_PROCESS_ROLE`` / ``MXNET_PROCESS_RANK`` for the child's
    ``run_start`` identity.  Returns the child context (or None when
    untraced).  Mutates and returns ``env``."""
    env[ROLE_ENV] = str(role)
    if rank is not None:
        env[RANK_ENV] = str(rank)
    if ctx is None:
        parent = current_context()
        if parent is None:
            if not enabled():
                env.pop(TRACE_ENV, None)
                return None
            parent = mint()
        ctx = parent.child()
    env[TRACE_ENV] = ctx.to_header()
    return ctx


# records written by an armed RunLog pick up the thread's bound trace
# context through this slot (kept a slot, not an import, so runlog
# stays cycle-free)
_runlog._TRACE_GETTER = current_context
