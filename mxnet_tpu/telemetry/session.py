"""Fit-loop telemetry session: the glue between ``Module.fit`` and the
process-wide :class:`~mxnet_tpu.telemetry.RunLog`.

One ``FitSession`` wraps one ``fit`` call: it stamps per-step records
(wall time, sampled loss sync, device-feed deltas), emits fit_start/
fit_end events, and owns the crash flight dumps for the three in-fit
death paths (SIGTERM drain, NaN-abort, unhandled exception).  All
methods are cheap no-ops when constructed with ``runlog=None`` so the
fit loop can call unconditionally through :func:`fit_session`.
"""
from __future__ import annotations

import time
from contextlib import nullcontext as _nullcontext

from . import tracing as _tracing

__all__ = ["FitSession", "fit_session"]


class FitSession:
    def __init__(self, runlog, batch_size=0, feed=None, watchdog=None):
        self.rl = runlog
        self.batch_size = int(batch_size)
        self._feed = feed
        self._feed_snap = feed.stats() if feed is not None else None
        self._t_step = None
        self._step_no = 0
        self._ended = False
        # hang watchdog, armed per fit by MXNET_WATCHDOG_SEC (works
        # with or without a run log: the stack dump is the point; the
        # 'watchdog' record rides along only when telemetry is armed).
        # step_begin beats it, finish() closes it.
        self._wd = None
        if watchdog is not False:
            try:
                from .watchdog import Watchdog, default_timeout

                if watchdog is not None:
                    self._wd = watchdog.arm("fit")
                elif default_timeout() > 0:
                    self._wd = Watchdog().arm("fit")
            except Exception:
                self._wd = None  # the observer must not break fit
        # fit is a trace entry point: one root context per fit call
        # (child of the process stamp when this worker was spawned by
        # a traced supervisor).  Sampled steps emit fit_step spans;
        # unsampled steps stay on the PR-5 hot-path budget.
        self._trace = None
        if runlog is not None:
            parent = _tracing.current_context()
            self._trace = parent.child() if parent is not None \
                else _tracing.mint()
            with _tracing.use(self._trace):
                runlog.event("fit_start", batch_size=self.batch_size)

    def __bool__(self):
        return self.rl is not None

    # ------------------------------------------------------------ steps
    def step_begin(self):
        if self._wd is not None:
            self._wd.beat("step")
        if self.rl is not None:
            self._t_step = time.perf_counter()

    def should_sync(self):
        """Sampled-sync decision for this step (the caller pays one
        device sync to read the loss/metric when True)."""
        return self.rl is not None and self.rl.should_sync(self._step_no)

    def step_end(self, epoch, batch, samples=None, loss=None,
                 synced=False, bad_step=False):
        if self.rl is None or self._t_step is None:
            return
        t0, t1 = self._t_step, time.perf_counter()
        wall = t1 - t0
        self._t_step = None
        feed_wait = h2d = None
        if self._feed is not None:
            snap = self._feed.stats()
            prev = self._feed_snap or {}
            feed_wait = snap.get("consumer_wait_s", 0.0) \
                - prev.get("consumer_wait_s", 0.0)
            h2d = snap.get("h2d_bytes", 0) - prev.get("h2d_bytes", 0)
            self._feed_snap = snap
        ctx = None
        if synced and self._trace is not None:
            # sampled steps only: the span rides the step record's
            # flush (flush=False) so traced fits pay zero extra
            # syscalls on the step path
            ctx = self._trace.child()
            _tracing.emit_span("fit_step", t0, t1, ctx, flush=False,
                               epoch=int(epoch), batch=int(batch))
        with (_tracing.use(ctx) if ctx is not None
              else _nullcontext()):
            self.rl.step(
                epoch, batch, wall,
                samples if samples is not None else self.batch_size,
                loss=loss, synced=synced, feed_wait_s=feed_wait,
                h2d_bytes=h2d, bad_step=bad_step)
        self._step_no += 1

    # ------------------------------------------------------ death paths
    def flight(self, reason):
        """First dump wins: the specific reason recorded at the raise
        site (nan_abort, preempt_drain) must not be overwritten by the
        generic exception handler unwinding past it."""
        if self.rl is None or getattr(self, "_flight_done", False):
            return None
        path = self.rl.flight_dump(reason)
        if path is not None:
            self._flight_done = True
        return path

    def finish(self, outcome="ok"):
        if self._wd is not None:
            self._wd.close()
            self._wd = None
        if self.rl is None or self._ended:
            return
        self._ended = True
        with (_tracing.use(self._trace) if self._trace is not None
              else _nullcontext()):
            self.rl.event("fit_end", outcome=outcome,
                          steps=self._step_no)
        if self.rl.textfile:
            self.rl.write_textfile()


def fit_session(batch_size=0, feed=None):
    """Build a FitSession against the active RunLog (a no-op shell when
    telemetry is off)."""
    from .runlog import current

    return FitSession(current(), batch_size=batch_size, feed=feed)
