"""Run-log record schema: one place tests, CI and the bench harness
agree on what a telemetry record must look like.

Every line of an ``MXNET_RUNLOG`` JSONL file is one record with a
``type`` discriminator; :func:`validate_record` returns a list of
human-readable problems (empty = valid).  The step-record field table
is the documented contract (README "Observability"):

========  =============================================================
type      meaning
========  =============================================================
run_start process/config/env fingerprint, written when the log opens
step      one training step (wall time, throughput, feed stats, ...)
compile   a program (re)trace with its cause (shape/dtype/...)
program_report  compiled-program introspection (memory/flops/collectives)
checkpoint  one atomic checkpoint write with its duration
watchdog  a hang-watchdog stall (phase, quiet seconds, stack dump path)
opstats   aggregate per-op table folded from the profiler's op events
tensor_stats  sampled numerics-monitor summary of named tensors
serve     one dispatched serving microbatch (size, pad, latency,
          queue depth, cumulative shed, breaker state)
generate  one generative-serving snapshot (tokens/s, TTFT p50/p99,
          sequences in flight, KV pages in use, cumulative
          eviction/shed counters, effective KV dtype)
fleet     one fleet-router observation (replica counts, queue-depth
          EWMA, cumulative request/failover/shed counters) stamped
          with the action that produced it (probe/eject/resize/swap)
heal      one self-healing runtime observation (peer_death /
          collective_abandon / emergency_ckpt / heal_exit / relaunch /
          resume) with the cumulative peer-death / emergency /
          relaunch counters
data      one data-plane observation (quarantine / respawn /
          epoch_end) with the cumulative records-skipped and
          worker-respawn counters stamped on
freshness one online-learning loop observation (publish / swap_commit
          / swap_shed / swap_rollback / violation / relaunch) carrying
          the artifact's monotonic model version, the measured
          sample-to-served freshness and the loop's cumulative
          export/swap/shed/violation counters
span      one completed trace span (name, duration, trace/span/parent
          ids) — the cross-process causal unit tools/tracemerge.py
          stitches into one timeline
event     everything else (bad_step, ps_retry, fault, deadline, ...)
run_end   final counters, written at close
========  =============================================================

Round 20: every record type may additionally carry the optional trace
fields ``trace_id`` / ``span_id`` / ``parent_span_id`` (validated when
present; absent = pre-round-20 compatible), and ``run_start`` may carry
the process identity ``role`` / ``rank`` / ``parent_pid`` stamped by
its spawner.
"""
from __future__ import annotations

__all__ = ["STEP_FIELDS", "RECORD_TYPES", "COMPILE_CAUSES",
           "OPSTATS_ROW_FIELDS", "TENSOR_STATS_ROW_FIELDS",
           "SERVE_FIELDS", "GENERATE_FIELDS", "FLEET_FIELDS",
           "HEAL_FIELDS", "DATA_FIELDS", "QUANT_FIELDS",
           "FRESHNESS_FIELDS", "SPAN_FIELDS", "TRACE_FIELDS",
           "validate_record", "validate_lines"]

#: step-record contract: field -> (types, required).  ``None`` is legal
#: for optional measurements (loss on an unsampled step, feed stats
#: when no device feed wraps the iterator).
STEP_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),            # seconds since run start
    "epoch": (int, True),
    "step": (int, True),                  # global step (monotonic)
    "batch": (int, True),                 # batch index within the epoch
    "wall_ms": ((int, float), True),
    "samples": (int, True),
    "samples_per_sec": ((int, float, type(None)), True),
    "loss": ((int, float, type(None)), True),
    "synced": (bool, True),               # sampled device sync happened
    "feed_wait_ms": ((int, float, type(None)), True),
    "h2d_bytes": ((int, type(None)), True),
    "collective_counts": ((dict, type(None)), True),
    "collective_bytes": ((int, type(None)), True),
    "sharding": (str, True),              # optimizer-sharding mode
    "bad_step": (bool, True),
    "ps_retries": (int, True),            # cumulative process counters
    "faults": (int, True),
    "checkpoints": (int, True),
}

RECORD_TYPES = ("run_start", "step", "compile", "program_report",
                "checkpoint", "watchdog", "opstats", "tensor_stats",
                "serve", "generate", "fleet", "heal", "data",
                "quantize", "freshness", "span", "event", "run_end")

#: contract of a ``span`` record (telemetry.tracing): one completed
#: span of a distributed trace.  ``t`` is the run-relative END time
#: (the runlog's native clock) and ``dur_ms`` walks it back to the
#: start, so tracemerge reconstructs wall time as
#: ``run_start.time + t - dur_ms/1e3``
SPAN_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),            # run-relative end time
    "name": (str, True),
    "kind": (str, True),                  # server|client|internal|...
    "dur_ms": ((int, float), True),
    "trace_id": (str, True),              # 32 hex
    "span_id": (str, True),               # 16 hex
    "parent_span_id": ((str, type(None)), True),
    "attrs": ((dict, type(None)), False),
}

#: optional trace stamps any OTHER record type may carry (absent =
#: pre-round-20 record) — validated for shape whenever present
TRACE_FIELDS = {
    "trace_id": (str, False),
    "span_id": (str, False),
    "parent_span_id": ((str, type(None)), False),
}


def _check_trace_ids(rec):
    """Hex-shape checks for trace stamps, applied whenever present."""
    problems = []
    tid = rec.get("trace_id")
    if isinstance(tid, str) and len(tid) != 32:
        problems.append(f"trace_id must be 32 hex chars, got {tid!r}")
    for name in ("span_id", "parent_span_id"):
        sid = rec.get(name)
        if isinstance(sid, str) and len(sid) != 16:
            problems.append(f"{name} must be 16 hex chars, got {sid!r}")
    return problems

#: per-batch contract of a ``serve`` record (serving.ModelServer)
SERVE_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),
    "model": (str, True),
    "batch": (int, True),                 # live requests in the batch
    "padded_to": (int, True),             # the bucketed batch shape
    "queue_depth": (int, True),           # queue at dispatch end
    "latency_ms": ((int, float), True),
    "deadline_margin_ms": ((int, float, type(None)), True),
    "shed": (int, True),                  # cumulative shed count
    "breaker": (str, True),
}

#: per-snapshot contract of a ``generate`` record
#: (serving.generate.GenerativeServer.report): the generative decode
#: path's health at one moment — throughput, time-to-first-token
#: percentiles, continuous-batching occupancy, paged-KV pool pressure
#: and the cumulative eviction/shed counters
GENERATE_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),
    "name": (str, True),
    "tokens": (int, True),                # cumulative generated tokens
    "tokens_s": ((int, float), True),
    "ttft_p50_ms": ((int, float, type(None)), True),
    "ttft_p99_ms": ((int, float, type(None)), True),
    "in_flight": (int, True),             # decode slots active now
    "max_in_flight": (int, True),
    "evictions": (int, True),             # cumulative KV preemptions
    "shed": (int, True),                  # cumulative rejections
    "pages_in_use": (int, True),          # paged-KV pool pressure
    "queue_depth": (int, True),           # prefill queue now
    "kv_dtype": (str, True),              # effective cache dtype
    "compiles": (int, True),              # post-warm compiles (0 proof)
}

#: per-observation contract of a ``fleet`` record (serving.fleet):
#: the router's view of its replica set at one moment, stamped with
#: the action that produced the record
FLEET_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),
    "action": (str, True),                # probe|eject|resize|swap|...
    "replicas": (int, True),              # replicas not ejected/dead
    "ready": (int, True),                 # routable replicas
    "queue_depth": (int, True),           # summed across the fleet
    "queue_ewma": ((int, float), True),   # the autoscaler's signal
    "requests": (int, True),              # cumulative router counters
    "failovers": (int, True),
    "shed": (int, True),
}

#: per-observation contract of a ``heal`` record (resilience.healing):
#: one self-healing runtime event — a declared peer death, an
#: abandoned collective, an emergency checkpoint flush, the survivor's
#: heal_exit, a supervisor relaunch or the healed resume — with the
#: process's cumulative healing counters stamped on
HEAL_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),
    "action": (str, True),        # peer_death|collective_abandon|...
    "peer_deaths": (int, True),   # cumulative process counters
    "emergency_ckpts": (int, True),
    "heal_relaunches": (int, True),
    "auto_reshards": (int, True),
}

#: per-observation contract of a ``data`` record (io data plane):
#: one quarantine / worker-respawn / epoch observation with the
#: process's cumulative skip and respawn counters stamped on — the
#: record chain that proves a shrunken epoch was DECLARED, not silent
DATA_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),
    "action": (str, True),        # quarantine|respawn|epoch_end|...
    "workers": (int, True),       # pool size (0 = single producer)
    "skipped": (int, True),       # cumulative data_records_skipped
    "respawns": (int, True),      # cumulative io_worker_respawns
}

#: per-observation contract of a ``quantize`` record
#: (mxnet_tpu.quantization): one calibrate / rewrite / race / export
#: observation — which mode ran and how many layers the pass touched,
#: so an armed run log names exactly what the int8 pipeline did
QUANT_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),
    "action": (str, True),        # calibrate|rewrite|race|export
    "mode": (str, True),          # naive|entropy ('' when n/a)
    "layers": (int, True),        # layers the action touched/adopted
    "excluded": (int, True),      # layers fenced off by the caller
}

#: per-observation contract of a ``freshness`` record
#: (mxnet_tpu.online): one online-loop event — a trainer export
#: published, a rolling swap committed/shed/rolled back, an SLO
#: violation or a supervisor relaunch — stamped with the artifact's
#: monotonic model version and the loop's cumulative counters, so a
#: run log alone proves the served version never regressed and names
#: every swap that was shed instead of silently skipped
FRESHNESS_FIELDS = {
    "type": (str, True),
    "t": ((int, float), True),
    "action": (str, True),        # publish|swap_commit|swap_shed|
                                  # swap_rollback|violation|relaunch
    "version": (int, True),       # monotonic model version (0 = n/a)
    "freshness_ms": ((int, float, type(None)), True),
    "exports": (int, True),       # cumulative loop counters
    "swaps": (int, True),
    "swaps_shed": (int, True),
    "violations": (int, True),
    "relaunches": (int, True),
}

#: per-op row contract of an ``opstats`` record (telemetry.opstats)
OPSTATS_ROW_FIELDS = {
    "count": (int, True),
    "total_us": ((int, float), True),
    "min_us": ((int, float), True),
    "max_us": ((int, float), True),
    "avg_us": ((int, float), True),
    "p99_us": ((int, float), True),
    "bytes": ((int, type(None)), True),
}

#: per-tensor row contract of a ``tensor_stats`` record
TENSOR_STATS_ROW_FIELDS = {
    "l2": ((int, float), True),
    "min": ((int, float), True),
    "max": ((int, float), True),
    "nan": (int, True),
    "inf": (int, True),
    "zero_frac": ((int, float), True),
}

#: the concrete retrace causes a compile record may carry
COMPILE_CAUSES = ("first_trace", "shape", "dtype", "train_mode",
                  "autotune_winner", "hyper_params", "sharding",
                  "program")


def _check_fields(rec, spec):
    problems = []
    for name, (types, required) in spec.items():
        if name not in rec:
            if required:
                problems.append(f"missing field {name!r}")
            continue
        if not isinstance(rec[name], types):
            problems.append(
                f"field {name!r} has type {type(rec[name]).__name__}, "
                f"want {types}")
    return problems


def validate_record(rec):
    """Validate one parsed record; returns a list of problems."""
    if not isinstance(rec, dict):
        return ["record is not an object"]
    t = rec.get("type")
    if t not in RECORD_TYPES:
        return [f"unknown record type {t!r}"]
    return _validate_typed(rec, t) + _check_fields(rec, TRACE_FIELDS) \
        + _check_trace_ids(rec)


def _validate_typed(rec, t):
    if t == "span":
        return _check_fields(rec, SPAN_FIELDS)
    if t == "step":
        return _check_fields(rec, STEP_FIELDS)
    if t == "compile":
        problems = _check_fields(rec, {
            "t": ((int, float), True), "program": (str, True),
            "cache": (str, True), "causes": (list, True),
            "fingerprint": (dict, True)})
        for c in rec.get("causes", ()):
            if c not in COMPILE_CAUSES:
                problems.append(f"unknown compile cause {c!r}")
        if rec.get("cache") not in ("hit", "miss"):
            problems.append(f"cache must be hit/miss, got "
                            f"{rec.get('cache')!r}")
        return problems
    if t == "program_report":
        return _check_fields(rec, {
            "t": ((int, float), True), "program": (str, True),
            "memory": (dict, True), "flops": ((int, float), True),
            "collectives": ((dict, type(None)), True)})
    if t == "checkpoint":
        return _check_fields(rec, {
            "t": ((int, float), True), "prefix": (str, True),
            "version": (int, True), "duration_s": ((int, float), True),
            "bytes": (int, True)})
    if t == "watchdog":
        return _check_fields(rec, {
            "t": ((int, float), True), "phase": (str, True),
            "quiet_s": ((int, float), True),
            "stack_path": ((str, type(None)), True)})
    if t == "opstats":
        problems = _check_fields(rec, {
            "t": ((int, float), True), "source": (str, True),
            "ops": (int, True), "rows": (dict, True)})
        for name, row in (rec.get("rows") or {}).items():
            if not isinstance(row, dict):
                problems.append(f"opstats row {name!r} is not an object")
                continue
            problems.extend(f"opstats row {name!r}: {p}"
                            for p in _check_fields(row,
                                                   OPSTATS_ROW_FIELDS))
        return problems
    if t == "tensor_stats":
        problems = _check_fields(rec, {
            "t": ((int, float), True), "step": (int, True),
            "where": (str, True), "nonfinite": (bool, True),
            "tensors": (dict, True)})
        for name, row in (rec.get("tensors") or {}).items():
            if not isinstance(row, dict):
                problems.append(
                    f"tensor_stats row {name!r} is not an object")
                continue
            problems.extend(
                f"tensor_stats row {name!r}: {p}"
                for p in _check_fields(row, TENSOR_STATS_ROW_FIELDS))
        return problems
    if t == "serve":
        return _check_fields(rec, SERVE_FIELDS)
    if t == "generate":
        return _check_fields(rec, GENERATE_FIELDS)
    if t == "fleet":
        return _check_fields(rec, FLEET_FIELDS)
    if t == "heal":
        return _check_fields(rec, HEAL_FIELDS)
    if t == "data":
        return _check_fields(rec, DATA_FIELDS)
    if t == "quantize":
        return _check_fields(rec, QUANT_FIELDS)
    if t == "freshness":
        return _check_fields(rec, FRESHNESS_FIELDS)
    if t == "event":
        return _check_fields(rec, {"t": ((int, float), True),
                                   "kind": (str, True)})
    if t == "run_start":
        return _check_fields(rec, {
            "time": ((int, float), True),
            "pid": (int, True),
            "env": (dict, True),
            "config": (dict, True),
            # round-20 process identity, stamped by spawners; optional
            # so pre-round-20 logs stay valid
            "role": (str, False),
            "rank": ((int, type(None)), False),
            "parent_pid": (int, False)})
    if t == "run_end":
        return _check_fields(rec, {"t": ((int, float), True),
                                   "counters": (dict, True)})
    return []


def validate_lines(lines):
    """Validate an iterable of JSONL lines; returns (records, problems)
    where problems carry the 1-based line number."""
    import json

    records, problems = [], []
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            problems.append(f"line {i}: not JSON ({e})")
            continue
        records.append(rec)
        problems.extend(f"line {i}: {p}" for p in validate_record(rec))
    return records, problems
