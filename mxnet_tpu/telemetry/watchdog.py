"""Hang watchdog: stack dumps for stalls no cooperative check can see.

BENCH_r05 died ``rc: 124`` with *zero* artifact: the process stalled
for 25 minutes inside an uninterruptible XLA call, where bench.py's
cooperative ``Deadline.exceeded()`` checks never run — the main thread
was blocked in C++ and Python control flow simply stopped.  The
reference framework has the same blind spot (a wedged engine worker
hangs ``WaitForAll`` forever); its escape hatch is attaching gdb.  Ours
is built in:

:class:`Watchdog` runs a daemon thread armed per phase (bench) or per
fit (``MXNET_WATCHDOG_SEC``).  Every unit of forward progress calls
:meth:`~Watchdog.beat`; when the heartbeat goes quiet for longer than
the timeout — *even with the main thread blocked in native code*, which
is the whole point — the watchdog thread:

* appends an all-thread stack dump (``faulthandler``, which walks
  frames without needing the stalled threads' cooperation) to the
  stack file, so the post-mortem says exactly WHERE the run wedged;
* flushes the PR-5 flight-recorder ring with reason ``stall`` and
  emits a ``watchdog`` record + ``watchdog_stalls`` counter into the
  active RunLog (both best-effort: telemetry may be unarmed);
* invokes the optional ``on_stall`` callback (bench.py rewrites its
  partial headline JSON here, so even a later ``kill -9`` leaves the
  stall attributed in the artifact).

The watchdog OBSERVES, it never kills — by default.  The external
``timeout -k`` (or the internal deadline) stays the executioner; the
watchdog's job is making sure the death is diagnosable.  After firing
it re-arms, so a long stall produces a bounded series of dumps
(``max_dumps``) showing whether the stack is moving or truly stuck.

``MXNET_WATCHDOG_ABORT`` (round 16, default OFF) is the escalation
for jobs whose orchestrator has no external executioner: once the
``max_dumps`` stall dumps are exhausted and the heartbeat is STILL
quiet for another full timeout, the watchdog flushes the flight ring,
fires the emergency checkpoint (``resilience.healing`` — the freshest
async snapshot, no collective needed), and ``os._exit``\\ s with
:data:`WATCHDOG_ABORT_EXIT_CODE` — a permanently wedged job gets
rescheduled instead of burning its whole wall budget.  The default
observe-only contract is unchanged.

Unarmed contract: ``MXNET_WATCHDOG_SEC`` unset/0 means no thread is
ever started and ``beat()`` is a single attribute check — the hot path
cost is nil.
"""
from __future__ import annotations

import faulthandler
import io
import os
import tempfile
import threading
import time

__all__ = ["Watchdog", "stack_path_for", "find_stack_dumps",
           "default_timeout", "WATCHDOG_ABORT_EXIT_CODE"]

#: exit status of a MXNET_WATCHDOG_ABORT escalation — distinct from
#: the faultsim crash code (87), a healing peer-death exit (83) and
#: any signal status, so the supervisor/orchestrator can tell "wedged
#: and self-aborted" from every other death
WATCHDOG_ABORT_EXIT_CODE = 85


def stack_path_for(runlog_path, pid=None):
    """The stack-dump file that pairs with a run log (like
    ``flight_path_for``): ``<runlog>.stacks.<pid>.txt``.  Pid-suffixed
    since round 20 — two processes armed with the same ``MXNET_RUNLOG``
    path (supervisor + child) used to interleave/clobber each other's
    dumps in one file."""
    return f"{runlog_path}.stacks.{os.getpid() if pid is None else pid}.txt"


def find_stack_dumps(runlog_path):
    """Every stack-dump file paired with a run log, newest first —
    pid-suffixed names plus the legacy unsuffixed
    ``<runlog>.stacks.txt`` (pre-round-20 artifacts stay loadable)."""
    import glob as _glob

    found = _glob.glob(f"{runlog_path}.stacks.*.txt")
    legacy = f"{runlog_path}.stacks.txt"
    if os.path.exists(legacy) and legacy not in found:
        found.append(legacy)
    found.sort(key=lambda p: (os.path.getmtime(p), p), reverse=True)
    return found


def default_timeout():
    """``MXNET_WATCHDOG_SEC`` from the registry; 0 = disarmed."""
    from ..config import get_env

    try:
        return float(get_env("MXNET_WATCHDOG_SEC"))
    except Exception:
        return 0.0


class Watchdog:
    """Background hang detector (see module docstring).

    Parameters
    ----------
    timeout : float or None
        Quiet seconds before a stall fires.  None reads
        ``MXNET_WATCHDOG_SEC``; <= 0 disables (no thread started).
    stack_path : str or None
        File the all-thread stack dumps append to.  None derives it
        from the active run log (``<runlog>.stacks.txt``) or falls
        back to a pid-keyed file in the temp dir.
    on_stall : callable or None
        ``on_stall(phase, quiet_s, stack_path)`` invoked from the
        watchdog thread after each dump (exceptions swallowed — an
        observer must not kill the observed).
    max_dumps : int
        Stack dumps per process — a truly wedged run re-fires every
        ``timeout`` seconds and this bounds the evidence file.
    """

    def __init__(self, timeout=None, stack_path=None, on_stall=None,
                 max_dumps=5, poll=None, abort=None):
        self.timeout = default_timeout() if timeout is None \
            else float(timeout)
        self._explicit_stack_path = stack_path
        self.on_stall = on_stall
        self.max_dumps = int(max_dumps)
        #: consecutive quiet periods in the CURRENT stall episode —
        #: reset by every beat.  `stalls` stays the lifetime dump
        #: budget; the abort escalation keys off the episode counter,
        #: so a job that stalled early, recovered and trained for
        #: hours is not executed on its next single-timeout hiccup
        self.episode_stalls = 0
        if abort is None:
            try:
                from ..config import get_env

                abort = bool(get_env("MXNET_WATCHDOG_ABORT"))
            except Exception:
                abort = False
        self.abort = bool(abort)
        self.stalls = 0
        self._poll = poll  # test hook; default derives from timeout
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._armed = False
        self._phase = None
        self._last_beat = time.monotonic()

    # ------------------------------------------------------------ paths
    @property
    def stack_path(self):
        if self._explicit_stack_path:
            return self._explicit_stack_path
        from . import runlog as _rl

        rl = _rl.current()
        if rl is not None:
            return stack_path_for(rl.path)
        return os.path.join(tempfile.gettempdir(),
                            f"mxnet_tpu_watchdog_{os.getpid()}.stacks.txt")

    # ---------------------------------------------------------- control
    def arm(self, phase="run"):
        """Arm for a phase: starts the thread on first use.  A <= 0
        timeout keeps everything off (no thread, beat() near-free)."""
        if self.timeout <= 0:
            return self
        with self._lock:
            self._phase = str(phase)
            self._last_beat = time.monotonic()
            self._armed = True
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._watch, name="mxnet_tpu-watchdog",
                    daemon=True)
                self._thread.start()
        return self

    def beat(self, phase=None):
        """Record forward progress (and optionally a phase change)."""
        if not self._armed:
            return
        with self._lock:
            self._last_beat = time.monotonic()
            self.episode_stalls = 0  # recovery ends the stall episode
            if phase is not None:
                self._phase = str(phase)

    def disarm(self):
        """Stop watching (the thread idles; re-``arm`` restarts)."""
        with self._lock:
            self._armed = False

    def close(self):
        self.disarm()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        # full close, not just disarm: the context-manager form must
        # not leak one polling daemon thread per with-block (re-arm
        # after close starts a fresh thread, so reuse still works)
        self.close()
        return False

    # ------------------------------------------------------------ watch
    def _watch(self):
        poll = self._poll if self._poll is not None \
            else max(0.05, min(self.timeout / 4.0, 5.0))
        while not self._stop.wait(poll):
            with self._lock:
                armed = self._armed
                quiet = time.monotonic() - self._last_beat
                phase = self._phase
            if not armed or quiet < self.timeout:
                continue
            self.episode_stalls += 1
            if self.abort and self.episode_stalls > self.max_dumps:
                # escalation (MXNET_WATCHDOG_ABORT): max_dumps quiet
                # periods IN THIS EPISODE are spent and the heartbeat
                # is STILL dead a full timeout later — this job is
                # wedged for good.  Leave every post-mortem artifact
                # and die with a distinct status so the orchestrator
                # reschedules instead of burning the wall budget.
                # (Keyed on the per-episode counter: an early
                # transient that exhausted the LIFETIME dump budget
                # must not arm a hair trigger for the rest of the
                # run.)
                self._abort(phase, quiet)
            if self.stalls < self.max_dumps:
                self._fire(phase, quiet)
            with self._lock:
                # re-arm in ALL cases (fired or dump-budget spent): a
                # quiet PERIOD — not a poll tick — is the unit the
                # episode counter and the dump series advance by, so
                # a still-stalled run escalates one full timeout at a
                # time and the dumps show whether the stacks move
                if time.monotonic() - self._last_beat >= self.timeout:
                    self._last_beat = time.monotonic()

    def _fire(self, phase, quiet_s):
        self.stalls += 1
        path = self.stack_path
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "a") as f:
                f.write(f"\n==== watchdog stall #{self.stalls} "
                        f"phase={phase} quiet={quiet_s:.1f}s "
                        f"pid={os.getpid()} t={time.time():.3f} ====\n")
                f.flush()
                self._dump_stacks(f)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            path = None  # a full disk must not kill the watchdog
        # best-effort telemetry: the RunLog may be unarmed (bench arms
        # the watchdog long before any run log exists)
        try:
            from . import runlog as _rl

            rl = _rl.current()
            if rl is not None:
                rl.count("watchdog_stalls")
                rl.watchdog(phase, quiet_s, path)
                rl.flight_dump("stall")
        except Exception:
            pass
        if self.on_stall is not None:
            try:
                self.on_stall(phase, quiet_s, path)
            except Exception:
                pass

    def _abort(self, phase, quiet_s):
        """The MXNET_WATCHDOG_ABORT escalation: flight ring, emergency
        checkpoint from the freshest snapshot, run log closed, then
        ``os._exit`` — a wedged native call cannot be unwound, only
        abandoned, and the exit code says why."""
        try:
            from ..resilience import healing

            healing.fire_emergency("watchdog_abort")
        except Exception:
            pass
        try:
            from . import runlog as _rl

            rl = _rl.current()
            if rl is not None:
                rl.heal("watchdog_abort", phase=str(phase),
                        quiet_s=round(float(quiet_s), 3),
                        stalls=self.stalls,
                        code=WATCHDOG_ABORT_EXIT_CODE)
                rl.flight_dump("watchdog_abort")
                rl.close()
        except Exception:
            pass
        os._exit(WATCHDOG_ABORT_EXIT_CODE)

    @staticmethod
    def _dump_stacks(f):
        """All-thread stacks via faulthandler (walks C-blocked threads'
        Python frames without their cooperation).  Falls back to the
        traceback module if faulthandler refuses the file object."""
        try:
            faulthandler.dump_traceback(file=f, all_threads=True)
            return
        except Exception:
            pass
        import traceback
        import sys

        buf = io.StringIO()
        for tid, frame in sys._current_frames().items():
            buf.write(f"Thread 0x{tid:x}:\n")
            traceback.print_stack(frame, file=buf)
        f.write(buf.getvalue())
