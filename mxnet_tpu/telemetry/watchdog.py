"""Hang watchdog: stack dumps for stalls no cooperative check can see.

BENCH_r05 died ``rc: 124`` with *zero* artifact: the process stalled
for 25 minutes inside an uninterruptible XLA call, where bench.py's
cooperative ``Deadline.exceeded()`` checks never run — the main thread
was blocked in C++ and Python control flow simply stopped.  The
reference framework has the same blind spot (a wedged engine worker
hangs ``WaitForAll`` forever); its escape hatch is attaching gdb.  Ours
is built in:

:class:`Watchdog` runs a daemon thread armed per phase (bench) or per
fit (``MXNET_WATCHDOG_SEC``).  Every unit of forward progress calls
:meth:`~Watchdog.beat`; when the heartbeat goes quiet for longer than
the timeout — *even with the main thread blocked in native code*, which
is the whole point — the watchdog thread:

* appends an all-thread stack dump (``faulthandler``, which walks
  frames without needing the stalled threads' cooperation) to the
  stack file, so the post-mortem says exactly WHERE the run wedged;
* flushes the PR-5 flight-recorder ring with reason ``stall`` and
  emits a ``watchdog`` record + ``watchdog_stalls`` counter into the
  active RunLog (both best-effort: telemetry may be unarmed);
* invokes the optional ``on_stall`` callback (bench.py rewrites its
  partial headline JSON here, so even a later ``kill -9`` leaves the
  stall attributed in the artifact).

The watchdog OBSERVES, it never kills: the external ``timeout -k`` (or
the internal deadline) stays the executioner; the watchdog's job is
making sure the death is diagnosable.  After firing it re-arms, so a
long stall produces a bounded series of dumps (``max_dumps``) showing
whether the stack is moving or truly stuck.

Unarmed contract: ``MXNET_WATCHDOG_SEC`` unset/0 means no thread is
ever started and ``beat()`` is a single attribute check — the hot path
cost is nil.
"""
from __future__ import annotations

import faulthandler
import io
import os
import tempfile
import threading
import time

__all__ = ["Watchdog", "stack_path_for", "default_timeout"]


def stack_path_for(runlog_path):
    """The stack-dump file that pairs with a run log (like
    ``flight_path_for``): ``<runlog>.stacks.txt``."""
    return f"{runlog_path}.stacks.txt"


def default_timeout():
    """``MXNET_WATCHDOG_SEC`` from the registry; 0 = disarmed."""
    from ..config import get_env

    try:
        return float(get_env("MXNET_WATCHDOG_SEC"))
    except Exception:
        return 0.0


class Watchdog:
    """Background hang detector (see module docstring).

    Parameters
    ----------
    timeout : float or None
        Quiet seconds before a stall fires.  None reads
        ``MXNET_WATCHDOG_SEC``; <= 0 disables (no thread started).
    stack_path : str or None
        File the all-thread stack dumps append to.  None derives it
        from the active run log (``<runlog>.stacks.txt``) or falls
        back to a pid-keyed file in the temp dir.
    on_stall : callable or None
        ``on_stall(phase, quiet_s, stack_path)`` invoked from the
        watchdog thread after each dump (exceptions swallowed — an
        observer must not kill the observed).
    max_dumps : int
        Stack dumps per process — a truly wedged run re-fires every
        ``timeout`` seconds and this bounds the evidence file.
    """

    def __init__(self, timeout=None, stack_path=None, on_stall=None,
                 max_dumps=5, poll=None):
        self.timeout = default_timeout() if timeout is None \
            else float(timeout)
        self._explicit_stack_path = stack_path
        self.on_stall = on_stall
        self.max_dumps = int(max_dumps)
        self.stalls = 0
        self._poll = poll  # test hook; default derives from timeout
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._armed = False
        self._phase = None
        self._last_beat = time.monotonic()

    # ------------------------------------------------------------ paths
    @property
    def stack_path(self):
        if self._explicit_stack_path:
            return self._explicit_stack_path
        from . import runlog as _rl

        rl = _rl.current()
        if rl is not None:
            return stack_path_for(rl.path)
        return os.path.join(tempfile.gettempdir(),
                            f"mxnet_tpu_watchdog_{os.getpid()}.stacks.txt")

    # ---------------------------------------------------------- control
    def arm(self, phase="run"):
        """Arm for a phase: starts the thread on first use.  A <= 0
        timeout keeps everything off (no thread, beat() near-free)."""
        if self.timeout <= 0:
            return self
        with self._lock:
            self._phase = str(phase)
            self._last_beat = time.monotonic()
            self._armed = True
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._watch, name="mxnet_tpu-watchdog",
                    daemon=True)
                self._thread.start()
        return self

    def beat(self, phase=None):
        """Record forward progress (and optionally a phase change)."""
        if not self._armed:
            return
        with self._lock:
            self._last_beat = time.monotonic()
            if phase is not None:
                self._phase = str(phase)

    def disarm(self):
        """Stop watching (the thread idles; re-``arm`` restarts)."""
        with self._lock:
            self._armed = False

    def close(self):
        self.disarm()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        # full close, not just disarm: the context-manager form must
        # not leak one polling daemon thread per with-block (re-arm
        # after close starts a fresh thread, so reuse still works)
        self.close()
        return False

    # ------------------------------------------------------------ watch
    def _watch(self):
        poll = self._poll if self._poll is not None \
            else max(0.05, min(self.timeout / 4.0, 5.0))
        while not self._stop.wait(poll):
            with self._lock:
                armed = self._armed
                quiet = time.monotonic() - self._last_beat
                phase = self._phase
            if not armed or quiet < self.timeout:
                continue
            if self.stalls >= self.max_dumps:
                continue
            self._fire(phase, quiet)
            with self._lock:
                # re-arm: a still-stalled run fires again after another
                # full quiet period, so the dump series shows whether
                # the stacks are moving
                self._last_beat = time.monotonic()

    def _fire(self, phase, quiet_s):
        self.stalls += 1
        path = self.stack_path
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "a") as f:
                f.write(f"\n==== watchdog stall #{self.stalls} "
                        f"phase={phase} quiet={quiet_s:.1f}s "
                        f"pid={os.getpid()} t={time.time():.3f} ====\n")
                f.flush()
                self._dump_stacks(f)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            path = None  # a full disk must not kill the watchdog
        # best-effort telemetry: the RunLog may be unarmed (bench arms
        # the watchdog long before any run log exists)
        try:
            from . import runlog as _rl

            rl = _rl.current()
            if rl is not None:
                rl.count("watchdog_stalls")
                rl.watchdog(phase, quiet_s, path)
                rl.flight_dump("stall")
        except Exception:
            pass
        if self.on_stall is not None:
            try:
                self.on_stall(phase, quiet_s, path)
            except Exception:
                pass

    @staticmethod
    def _dump_stacks(f):
        """All-thread stacks via faulthandler (walks C-blocked threads'
        Python frames without their cooperation).  Falls back to the
        traceback module if faulthandler refuses the file object."""
        try:
            faulthandler.dump_traceback(file=f, all_threads=True)
            return
        except Exception:
            pass
        import traceback
        import sys

        buf = io.StringIO()
        for tid, frame in sys._current_frames().items():
            buf.write(f"Thread 0x{tid:x}:\n")
            traceback.print_stack(frame, file=buf)
        f.write(buf.getvalue())
