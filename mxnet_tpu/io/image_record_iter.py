"""ImageRecordIter — the high-throughput record→decode→augment→batch
pipeline.

Reference parity: src/io/iter_image_recordio_2.cc:880
(ImageRecordIter2: dmlc chunk reader → preprocess_threads decode+augment
workers → batch assembly → PrefetcherIter double buffering) and its
MXNET_REGISTER_IO_ITER("ImageRecordIter") python surface
(mx.io.ImageRecordIter kwargs).

TPU-native design: the whole .rec is memory-mapped and framed by the
native C++ parser; batches of JPEGs decode+augment in C++ worker
threads straight into NCHW float32 buffers (GIL released); a background
Python thread keeps ``prefetch_buffer`` batches ready so the
accelerator never waits on the host.  PIL fallback keeps functionality
without the native lib.

Fault-tolerant data plane (round 17): the pipeline degrades
structurally instead of dying —

* **corrupt-record quarantine** — a record that fails framing
  (resync-on-magic in :class:`..recordio.MXRecordIO`), unpack or
  decode is SKIPPED: counted on the ``data_records_skipped`` telemetry
  counter, named (file / parsed-stream ordinal / exact byte offset /
  reason) in an atomically-rewritten quarantine manifest, and dropped
  from every later batch.  Ordinals number the PARSED stream — a
  framing gap shifts everything after it, so the byte offset is the
  repair key.  Crossing ``MXNET_IO_MAX_SKIP_FRAC`` fails loudly with
  the manifest attached — the pipeline never silently trains on a
  shrunken dataset.
* **worker pool** — ``MXNET_IO_WORKERS`` (default 0 preserves the
  single-producer behavior) decode+augment workers behind a
  sequence-ordered emitter.  A worker that dies holding a batch
  (the ``io.worker`` fault point's ``crash``) or wedges past the
  per-batch deadline (default: the armed ``MXNET_WATCHDOG_SEC``) is
  detected, its batch re-dispatched, and a replacement spawned under
  the ``MXNET_IO_WORKER_RESPAWN`` budget; exhausting the budget is a
  loud structured failure, never a hang.
* **sample-exact determinism through faults** — batches are assembled
  by INDEX PLAN, not arrival order: which record lands in which batch
  row is a pure function of (epoch plan, quarantine set), so worker
  count, respawns and stragglers cannot perturb the sample stream, a
  resumed run replays it exactly, and an
  :class:`..resilience.elastic.ElasticHostIter` re-slice at a
  different host count yields the identical surviving-sample union
  (quarantined rows compact out and refill as tail pad).
"""
from __future__ import annotations

import heapq
import json
import mmap
import os
import queue
import threading
import time

import numpy as onp

from .. import recordio
from ..base import MXNetError
from ..telemetry import tracing as _tracing
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageDetRecordIter", "ImageRecordIter"]


class ImageRecordIter(DataIter):
    """mx.io.ImageRecordIter (reference C++ iterator surface).

    Supported kwargs mirror the reference's ImageRecordParam /
    augmenter params: path_imgrec, data_shape, batch_size, shuffle,
    rand_crop, rand_mirror, resize, mean_r/g/b, std_r/g/b,
    preprocess_threads, prefetch_buffer, label_width, round_batch,
    part_index/num_parts (sharding), seed — plus the round-17 data
    plane knobs: io_workers (MXNET_IO_WORKERS), worker_respawn
    (MXNET_IO_WORKER_RESPAWN), max_skip_frac (MXNET_IO_MAX_SKIP_FRAC),
    quarantine_manifest (default ``<path_imgrec>.quarantine.json``)
    and worker_deadline_sec (default: MXNET_WATCHDOG_SEC when armed,
    else 30 s).
    """

    #: label value for all-quarantined placeholder pad rows (the det
    #: subclass overrides with its -1 "no object" convention)
    _label_fill_value = 0.0

    #: ImageNet PCA lighting basis (reference src/io/image_aug_default.cc
    #: — the AlexNet eigen decomposition, 0..255 pixel scale)
    _PCA_EIGVAL = onp.array([55.46, 4.794, 1.148], "float32")
    _PCA_EIGVEC = onp.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.8140],
                             [-0.5836, -0.6948, 0.4203]], "float32")

    def __init__(self, path_imgrec, data_shape, batch_size, shuffle=False,
                 rand_crop=False, rand_mirror=False, resize=-1,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0,
                 std_g=1.0, std_b=1.0, preprocess_threads=None,
                 prefetch_buffer=None, label_width=1, round_batch=True,
                 part_index=0, num_parts=1, seed=0, dtype="float32",
                 random_h=0, random_s=0, random_l=0, pca_noise=0.0,
                 max_random_contrast=0.0, max_random_illumination=0.0,
                 device_feed=None, io_workers=None, worker_respawn=None,
                 max_skip_frac=None, quarantine_manifest=None,
                 worker_deadline_sec=None, **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (c, h, w)")
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._mean = onp.array([mean_r, mean_g, mean_b], "float32")
        self._std = onp.array([std_r, std_g, std_b], "float32")
        # color-space augmenters (reference image_aug_default.cc:565
        # RandomHueSaturationLight): HSL jitter ranges follow the
        # reference's OpenCV-HLS units (H 0..180, S/L 0..255)
        self._random_h = float(random_h)
        self._random_s = float(random_s)
        self._random_l = float(random_l)
        self._pca_noise = float(pca_noise)
        self._max_contrast = float(max_random_contrast)
        self._max_illumination = float(max_random_illumination)
        self._color_jitter = any((self._random_h, self._random_s,
                                  self._random_l, self._pca_noise,
                                  self._max_contrast,
                                  self._max_illumination))
        from .. import config as _config

        self._threads = (preprocess_threads if preprocess_threads
                         is not None
                         else _config.get_env("MXNET_CPU_WORKER_NTHREADS"))
        self._prefetch = (prefetch_buffer if prefetch_buffer is not None
                          else _config.get_env("MXNET_TPU_PREFETCH_BUFFER"))
        self._round_batch = round_batch
        self._rng = onp.random.RandomState(seed)  # shuffle order only
        self._seed_base = int(seed)
        self._epoch = -1  # first reset() -> epoch 0 (per-batch rng key)
        self._dtype = dtype
        if device_feed is None:
            from .device_feed import device_feed_enabled

            device_feed = device_feed_enabled()
        # device feed: the producer thread builds the DEVICE batch
        # (nd.array = host->HBM device_put), so up to prefetch_buffer
        # batches sit HBM-resident while the consumer's step runs —
        # next() hands them over without a blocking transfer
        self._device_feed = bool(device_feed)

        # -------- round-17 data plane knobs --------
        self._io_workers = int(
            io_workers if io_workers is not None
            else _config.get_env("MXNET_IO_WORKERS"))
        self._respawn_budget = int(
            worker_respawn if worker_respawn is not None
            else _config.get_env("MXNET_IO_WORKER_RESPAWN"))
        self._max_skip_frac = float(
            max_skip_frac if max_skip_frac is not None
            else _config.get_env("MXNET_IO_MAX_SKIP_FRAC"))
        if worker_deadline_sec is not None:
            self._worker_deadline = float(worker_deadline_sec)
        else:
            wd = float(_config.get_env("MXNET_WATCHDOG_SEC") or 0.0)
            # the per-batch deadline rides the watchdog heartbeat: a
            # pool wedged longer than the stall detector's period is
            # re-dispatched before the watchdog would dump stacks
            self._worker_deadline = wd if wd > 0 else 30.0
        self._path = os.fspath(path_imgrec)
        self._manifest_path = (os.fspath(quarantine_manifest)
                               if quarantine_manifest is not None
                               else self._path + ".quarantine.json")
        self._qlock = threading.RLock()
        self._quarantined = set()   # indices into self._records
        self._qentries = []         # manifest rows
        self._parse_skips = 0       # framing-level resync EVENTS
        self._parse_skip_bytes = 0  # total bytes the resyncs jumped
        self._respawns = 0         # cumulative spawns (stats, monotonic)
        self._respawn_charges = 0  # budget ledger (refundable: a slow
        #   worker that still DELIVERS hands its charge back)
        self._manifest_warned = False
        self._manifest_dirty = False

        # mmap + frame the record file once (host page cache does the
        # streaming; the reference reads chunks instead)
        self._file = open(path_imgrec, "rb")
        try:
            self._mm = mmap.mmap(self._file.fileno(), 0,
                                 access=mmap.ACCESS_READ)
            from .. import _native

            self._records = None
            if _native.get_lib() is not None:
                try:
                    self._records, self._offsets = \
                        _native.parse_records(self._mm,
                                              return_offsets=True)
                except Exception as exc:
                    # the native parser rejects the whole file on any
                    # framing damage — the resync python parser
                    # recovers every intact record and names the gaps
                    from .. import telemetry

                    telemetry.event(
                        "io_parse_fallback", file=self._path,
                        reason=f"{type(exc).__name__}: {exc}")
                    self._records = None
            if self._records is None:
                self._records = self._parse_python()
            self._rec_ids = list(range(len(self._records)))
            self._parsed_full = len(self._records)  # pre-shard count
            if num_parts > 1:
                self._records = self._records[part_index::num_parts]
                self._offsets = self._offsets[part_index::num_parts]
                self._rec_ids = self._rec_ids[part_index::num_parts]
            if not self._records:
                raise MXNetError(f"no records in {path_imgrec}")
            if not self._qentries \
                    and os.path.exists(self._manifest_path):
                # a repaired/replaced shard must not keep a previous
                # run's quarantine evidence: rewrite truthful (empty)
                self._manifest_dirty = True
            self._flush_manifest()
            self._check_ceiling()
        except BaseException:
            # a loud constructor failure (skip ceiling, unparseable
            # file) must not leak the fd + mapping: the operator loop
            # that catches it and rotates shards would bleed fds
            self._records = None
            try:
                if getattr(self, "_mm", None) is not None:
                    self._mm.close()
            except (BufferError, ValueError):
                pass
            self._file.close()
            raise
        self._order = onp.arange(len(self._records))
        self._queue = None
        self._worker = None
        self._emitter = None
        self._pool_threads = []
        self._pool = None
        self._stop = threading.Event()
        if not getattr(self, "_defer_start", False):
            # subclasses with extra config (ImageDetRecordIter) start
            # the producer themselves once fully constructed
            self.reset()

    def _parse_python(self):
        # pure-python fallback: ONE source of framing truth —
        # MXRecordIO.read with resync-on-magic armed, so a torn frame
        # is a named quarantine entry instead of a dead dataset
        records = []
        offsets = []
        recovered = {"pos": None}

        def on_skip(offset, nbytes, reason):
            # a record returned after a resync gap STARTS at the gap's
            # end, not at the pre-read position — track it so the
            # manifest names the record's true byte offset
            recovered["pos"] = offset + nbytes
            self._note_parse_skip(offset, nbytes, reason)

        reader = recordio.MXRecordIO(self._file.name, "r", resync=True,
                                     on_skip=on_skip)
        try:
            while True:
                recovered["pos"] = None
                pos = reader.tell()
                rec = reader.read()
                if rec is None:
                    break
                records.append(memoryview(rec))
                offsets.append(recovered["pos"]
                               if recovered["pos"] is not None else pos)
        finally:
            reader.close()
        self._offsets = offsets
        return records

    # ------------------------------------------------------- quarantine
    def _note_parse_skip(self, offset, nbytes, reason):
        """One resync gap from the framing reader: count + manifest
        row (record ordinal unknowable — the frame never parsed)."""
        with self._qlock:
            self._parse_skips += 1
            self._parse_skip_bytes += int(nbytes)
            self._manifest_dirty = True
            self._qentries.append({
                "file": self._path, "record": None,
                "offset": int(offset), "bytes_skipped": int(nbytes),
                "stage": "read", "reason": str(reason)[:400]})
        from .. import telemetry

        telemetry.count("data_records_skipped")
        rl = telemetry.current()
        if rl is not None:
            rl.data_plane("quarantine", workers=self._io_workers,
                          file=self._path, stage="read",
                          offset=int(offset))

    def _quarantine(self, j, stage, exc):
        """Quarantine record ``j`` (index into this shard): once per
        record — wrap-fill duplicates and later epochs re-encounter it
        and drop the row silently instead of recounting."""
        with self._qlock:
            if j in self._quarantined:
                return
            self._quarantined.add(j)
            self._manifest_dirty = True
            entry = {"file": self._path, "record": self._rec_ids[j],
                     "offset": self._offsets[j], "stage": stage,
                     "reason": f"{type(exc).__name__}: {exc}"[:400]}
            self._qentries.append(entry)
        from .. import telemetry

        telemetry.count("data_records_skipped")
        rl = telemetry.current()
        if rl is not None:
            rl.data_plane("quarantine", workers=self._io_workers,
                          file=self._path, stage=stage,
                          record=self._rec_ids[j])
        self._check_ceiling()

    def _flush_manifest(self):
        """Atomically rewrite the quarantine manifest — the artifact a
        loud failure (skip ceiling, respawn exhaustion) points the
        operator at.  Rows are sorted so the manifest is byte-stable
        regardless of worker count or arrival order.  DEBOUNCED: skips
        mark it dirty and the flush happens at epoch end, on every
        loud-failure path, and at close() — a heavily corrupt shard
        must not pay one fsync+rename per quarantined record on the
        decode hot path."""
        with self._qlock:
            if not self._manifest_dirty:
                return
            self._manifest_dirty = False
            entries = sorted(
                self._qentries,
                key=lambda e: (e["record"] is None,
                               e["record"] if e["record"] is not None
                               else -1,
                               e["offset"] if e["offset"] is not None
                               else -1))
            doc = {"file": self._path,
                   "records": len(self._records),
                   "skipped": self._parse_skips + len(self._quarantined),
                   # "record" ordinals number the PARSED stream (a
                   # framing gap shifts everything after it); "offset"
                   # is the exact byte position — repair by offset
                   "ordinal_space": "parsed_stream",
                   "entries": entries}
        try:
            from ..resilience.checkpoint import atomic_write_bytes

            atomic_write_bytes(self._manifest_path,
                               json.dumps(doc, indent=1).encode(),
                               inject_point=None)
        except OSError:
            if not self._manifest_warned:
                self._manifest_warned = True
                import logging

                logging.warning(
                    "ImageRecordIter: cannot write quarantine "
                    "manifest %s (skips still counted)",
                    self._manifest_path)

    def _parse_records_lost(self):
        """Estimated RECORDS lost to framing damage: one resync event
        can jump a whole corrupt extent (thousands of records), so the
        ceiling must weigh bytes skipped against the mean record size,
        not count events."""
        if not self._parse_skips:
            return 0
        good_bytes = max(1, len(self._mm) - self._parse_skip_bytes)
        mean = good_bytes / max(1, self._parsed_full)
        est = int(round(self._parse_skip_bytes / max(mean, 1.0)))
        return max(self._parse_skips, est)

    def _check_ceiling(self):
        # parse skips are FILE-level (counted before the num_parts
        # slice) while decode quarantines are SHARD-level — measure
        # each against its own population and bound the sum, so a
        # sharded read cannot overstate corruption by ~num_parts
        with self._qlock:
            lost = self._parse_records_lost()
            skipped = self._parse_skips + len(self._quarantined)
            parse_frac = lost / max(1, self._parsed_full + lost)
            decode_frac = len(self._quarantined) / max(
                1, len(self._records) if self._records else 1)
        frac = parse_frac + decode_frac
        if skipped and frac > self._max_skip_frac:
            self._flush_manifest()  # the error names it: make it fresh
            raise MXNetError(
                f"data quarantine ceiling exceeded: {skipped} records "
                f"skipped (fraction {frac:.3f} > "
                f"MXNET_IO_MAX_SKIP_FRAC={self._max_skip_frac}) — "
                f"refusing to silently train on a shrunken dataset.  "
                f"Quarantine manifest: {self._manifest_path}")

    def data_plane_stats(self):
        """Snapshot of the round-17 data plane counters for this
        iterator: records in the shard, cumulative skips (framing
        resyncs + decode quarantines), worker respawns, pool size and
        the manifest path."""
        with self._qlock:
            return {"workers": self._io_workers,
                    "records": len(self._records),
                    "skipped": self._parse_skips + len(self._quarantined),
                    "parse_skips": self._parse_skips,
                    "quarantined": len(self._quarantined),
                    "respawns": self._respawns,
                    "manifest": self._manifest_path}

    # ----------------------------------------------------------- pipeline
    def _batch_rng(self, seq):
        """Per-batch RandomState keyed on (seed, epoch, batch seq) so
        augmentation draws are a pure function of the index plan —
        identical at any worker count, after any respawn, and on a
        re-dispatched batch.  Seeded with the TUPLE (array-seed form),
        so distinct (epoch, seq) pairs can never collide the way an
        arithmetic mix would past 8191 batches/epoch."""
        return onp.random.RandomState(
            [self._seed_base & 0xFFFFFFFF,
             self._epoch & 0xFFFFFFFF, int(seq) & 0xFFFFFFFF])

    def _build_plan(self):
        """The epoch's index plan: batch ``seq`` always covers the same
        order rows, quarantines notwithstanding — the determinism
        contract batches, cursors and host re-slices all lean on."""
        bs = self.batch_size
        order = self._order
        n = len(order)
        plan = []
        i = 0
        seq = 0
        while i < n:
            take = min(bs, n - i)
            idx = order[i:i + take]
            i += take
            pad = bs - take
            if pad and self._round_batch:
                # wrap around to fill, report pad; onp.resize cycles
                # when the dataset/shard is smaller than a batch
                idx = onp.concatenate([idx, onp.resize(order, pad)])
            # round_batch=False: final batch is genuinely smaller
            plan.append((seq, idx, take))
            seq += 1
        return plan

    @staticmethod
    def _put(q, stop, item):
        """Stop-aware bounded put: a producer blocked against a consumer
        that stopped draining (abandoned iterator) exits within one
        timeout of ``close()``/``reset()`` instead of leaking a thread
        wedged in ``queue.put`` forever.  Delegates to the ONE
        shutdown-critical loop (``device_feed._q_put``) so the two
        pipelines cannot drift.  ``q``/``stop`` are the THREAD'S OWN
        epoch's objects — an abandoned thread from a previous reset can
        never touch the new epoch's queue."""
        from .device_feed import _q_put

        return _q_put(q, stop, item)

    def _producer(self, q, stop, plan):
        try:
            self._producer_impl(q, stop, plan)
        except Exception as exc:  # surface in next(), don't hang it
            self._flush_manifest()
            if not stop.is_set():
                self._put(q, stop, ("error", exc))

    def _producer_impl(self, q, stop, plan):
        for seq, idx, take in plan:
            if stop.is_set():
                return
            batch, lab_arr, pad_out = self._assemble(seq, idx, take)
            if stop.is_set():
                return
            if self._device_feed:
                ok = self._put(q, stop,
                               ("ready",
                                self._emit(batch, lab_arr, pad_out)))
            else:
                ok = self._put(q, stop, (batch, lab_arr, pad_out))
            if not ok:
                return
        self._flush_manifest()  # epoch end: debounced quarantine rows
        self._put(q, stop, None)

    def _assemble(self, seq, idx, n_real):
        """Decode+augment one planned index batch.  Quarantined rows
        compact out; the tail refills by repeating the last survivor so
        the batch shape stays static (no retrace), and every refilled
        or surviving-wrap row is accounted as pad.  Returns
        ``(batch, labels, pad)``."""
        batch, labels, kept = self._make_batch(idx, self._batch_rng(seq))
        want = len(idx)
        n_ok = len(kept)
        real_ok = sum(1 for k in kept if k < n_real)
        if n_ok < want:
            if n_ok:
                fill_b, fill_l = batch[-1:], labels[-1:]
            else:  # every row quarantined: an all-pad placeholder batch
                fill_b = onp.zeros((1,) + tuple(batch.shape[1:]),
                                   batch.dtype)
                fill_l = onp.full((1,) + tuple(labels.shape[1:]),
                                  self._label_fill_value, labels.dtype)
            reps = want - n_ok
            batch = onp.concatenate([batch] + [fill_b] * reps)
            labels = onp.concatenate([labels] + [fill_l] * reps)
        return batch, labels, want - real_ok

    def _emit(self, batch, labels, pad):
        """numpy batch -> DataBatch of device NDArrays; in device-feed
        mode this runs in the PRODUCER thread so the H2D transfer
        overlaps the consumer's running step."""
        from .. import ndarray as nd

        data = nd.array(batch.astype(self._dtype)
                        if self._dtype != "float32" else batch,
                        dtype=self._dtype)
        lab = nd.array(labels[:, 0]
                       if (self.label_width == 1 and labels.ndim == 2)
                       else labels)
        return DataBatch(data=[data], label=[lab], pad=pad)

    def _load_record(self, j):
        """Unpack record ``j`` with quarantine: (header, payload), or
        None when the record is (or just became) quarantined."""
        from ..resilience import faultsim

        recs = self._records
        if recs is None:
            # the iterator was closed under an abandoned (join-timed-
            # out) worker: abort the batch, never fabricate quarantine
            # rows from a torn-down object
            raise MXNetError("ImageRecordIter is closed")
        with self._qlock:
            if j in self._quarantined:
                return None
        try:
            faultsim.inject("io.decode")
            return recordio.unpack(bytes(recs[j]))
        except Exception as exc:
            self._quarantine(j, "unpack", exc)
            return None

    def _draw_aug(self, n, rng):
        """Draw EVERY augmentation parameter for all ``n`` PLANNED
        rows up front — draws are indexed by plan position, so the
        quarantine set's state at assembly time (which varies with
        assembly order, resumes and re-dispatches) can never shift the
        crop/mirror/jitter of a surviving row."""
        d = {"crop_x": (rng.rand(n).astype("float32") if self._rand_crop
                        else onp.full(n, 0.5, "float32")),
             "crop_y": (rng.rand(n).astype("float32") if self._rand_crop
                        else onp.full(n, 0.5, "float32")),
             "mirror": ((rng.rand(n) < 0.5).astype("uint8")
                        if self._rand_mirror
                        else onp.zeros(n, "uint8"))}
        if self._max_contrast > 0:
            d["contrast"] = (1.0 + rng.uniform(
                -self._max_contrast, self._max_contrast, n)) \
                .astype("float32")
        if self._max_illumination > 0:
            d["illum"] = rng.uniform(-self._max_illumination,
                                     self._max_illumination, n) \
                .astype("float32")
        if self._random_h:
            d["dh"] = rng.uniform(-self._random_h, self._random_h, n)
        if self._random_s:
            d["ds"] = rng.uniform(-self._random_s, self._random_s, n)
        if self._random_l:
            d["dl"] = rng.uniform(-self._random_l, self._random_l, n)
        if self._pca_noise > 0:
            d["pca"] = rng.normal(0.0, self._pca_noise, (n, 3)) \
                .astype("float32")
        return d

    def _make_batch(self, idx, rng):
        """Decode+augment one index batch with per-record quarantine;
        returns compacted ``(batch, labels, kept_positions)`` where
        ``kept_positions`` are the surviving positions within ``idx``
        (plan order preserved).  Subclasses override for different
        label/augment semantics (ImageDetRecordIter)."""
        c, h, w = self.data_shape
        draws = self._draw_aug(len(idx), rng)
        jpegs, labs, kept = [], [], []
        for pos, j in enumerate(idx):
            payload = self._load_record(int(j))
            if payload is None:
                continue
            header, img = payload
            lab = onp.atleast_1d(onp.asarray(header.label, "float32"))
            jpegs.append(img)
            labs.append(lab[:self.label_width])
            kept.append(pos)
        rec_ids = [int(idx[k]) for k in kept]
        sel = onp.asarray(kept, dtype=int)
        sub = {k: v[sel] for k, v in draws.items()}
        batch, ok = self._decode_batch(jpegs, h, w, sub, rec_ids)
        batch = batch[ok]
        labs = [la for la, o in zip(labs, ok) if o]
        kept = [k for k, o in zip(kept, ok) if o]
        lab_arr = onp.zeros((len(kept), self.label_width), "float32")
        for kk, lab in enumerate(labs):
            lab_arr[kk, :len(lab)] = lab
        return batch, lab_arr, kept

    def _decode_native(self, jpegs, h, w, crop_x, crop_y, mirror,
                       draws):
        from .. import _native

        if self._color_jitter:
            # decode raw 0..255 (native normalization off), jitter
            # in color space, then normalize here — the reference
            # default-aug chain orders it the same way
            # (image_aug_default.cc: hsl/pca before mean subtract)
            raw, failed = _native.decode_augment_batch(
                jpegs, h, w,
                mean=onp.zeros(3, "float32"),
                std=onp.ones(3, "float32"),
                crop_x=crop_x, crop_y=crop_y, mirror=mirror,
                resize_short=self._resize,
                num_threads=self._threads)
            if failed:
                # fall back to the per-image path: a silently-zeroed
                # row must become a NAMED quarantine entry instead
                raise MXNetError(
                    f"native decode failed {failed} record(s)")
            raw = self._apply_color_jitter(raw, draws)
            return ((raw - self._mean[None, :, None, None])
                    / self._std[None, :, None, None])
        batch, failed = _native.decode_augment_batch(
            jpegs, h, w, mean=self._mean, std=self._std,
            crop_x=crop_x, crop_y=crop_y, mirror=mirror,
            resize_short=self._resize, num_threads=self._threads)
        if failed:
            raise MXNetError(
                f"native decode failed {failed} record(s)")
        return batch

    def _decode_one(self, jpeg, h, w, crop_x, crop_y, mirror):
        """PIL fallback for one image (slow path, functional parity);
        normalization applies here unless color jitter defers it."""
        from .. import image as img_mod

        im = img_mod.imdecode(jpeg)
        if self._resize > 0:
            im = img_mod.resize_short(im, self._resize)
        ih, iw = im.shape[:2]
        if ih >= h and iw >= w:
            x0 = int(crop_x * (iw - w))
            y0 = int(crop_y * (ih - h))
            im = img_mod.fixed_crop(im, x0, y0, w, h)
        else:
            im = img_mod.imresize(im, w, h)
        arr = im.asnumpy().astype("float32")
        if mirror:
            arr = arr[:, ::-1]
        if not self._color_jitter:
            arr = (arr - self._mean) / self._std
        return arr.transpose(2, 0, 1)

    def _decode_batch(self, jpegs, h, w, draws, rec_ids):
        """Decode+augment; returns ``(batch, ok_mask)`` — a row that
        fails to decode is quarantined (named by ``rec_ids``) rather
        than raised through the pipeline.  ``draws`` carries the
        per-row augmentation parameters (already position-aligned by
        the caller)."""
        from .. import _native

        nimg = len(jpegs)
        crop_x, crop_y = draws["crop_x"], draws["crop_y"]
        mirror = draws["mirror"]
        if nimg and _native.get_lib() is not None:
            try:
                return (self._decode_native(jpegs, h, w, crop_x,
                                            crop_y, mirror, draws),
                        onp.ones(nimg, bool))
            except Exception as exc:
                # the per-image path below names the bad record — but
                # say so: a SYSTEMIC native failure silently falling
                # back every batch would be a large invisible
                # throughput regression
                from .. import telemetry

                telemetry.event(
                    "io_decode_fallback", records=nimg,
                    reason=f"{type(exc).__name__}: {exc}"[:200])
        out = onp.zeros((nimg, 3, h, w), "float32")
        ok = onp.zeros(nimg, bool)
        for k in range(nimg):
            try:
                out[k] = self._decode_one(jpegs[k], h, w,
                                          float(crop_x[k]),
                                          float(crop_y[k]),
                                          bool(mirror[k]))
                ok[k] = True
            except Exception as exc:
                self._quarantine(rec_ids[k], "decode", exc)
        if self._color_jitter:
            out = self._apply_color_jitter(out, draws)
            out = ((out - self._mean[None, :, None, None])
                   / self._std[None, :, None, None])
        return out, ok

    # ------------------------------------------- color-space augmenters
    @staticmethod
    def _rgb_to_hsl(rgb):
        """Vectorized RGB(0..1) -> (H deg 0..360, S 0..1, L 0..1)."""
        r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
        maxc = onp.max(rgb, axis=-1)
        minc = onp.min(rgb, axis=-1)
        delta = maxc - minc
        lum = (maxc + minc) / 2.0
        denom = 1.0 - onp.abs(2.0 * lum - 1.0)
        sat = onp.where(delta > 0, delta / onp.maximum(denom, 1e-12), 0.0)
        safe = onp.maximum(delta, 1e-12)
        hr = onp.where(maxc == r, ((g - b) / safe) % 6.0, 0.0)
        hg = onp.where(maxc == g, (b - r) / safe + 2.0, 0.0)
        hb = onp.where(maxc == b, (r - g) / safe + 4.0, 0.0)
        # priority r > g > b on ties, like colorsys
        hue = onp.where(maxc == r, hr, onp.where(maxc == g, hg, hb))
        hue = onp.where(delta > 0, hue * 60.0, 0.0)
        return hue, sat, lum

    @staticmethod
    def _hsl_to_rgb(hue, sat, lum):
        c = (1.0 - onp.abs(2.0 * lum - 1.0)) * sat
        hp = (hue % 360.0) / 60.0
        x = c * (1.0 - onp.abs(hp % 2.0 - 1.0))
        z = onp.zeros_like(c)
        conds = [(hp < 1), (hp < 2), (hp < 3), (hp < 4), (hp < 5)]
        r = onp.select(conds, [c, x, z, z, x], c)
        g = onp.select(conds, [x, c, c, x, z], z)
        b = onp.select(conds, [z, z, x, c, c], x)
        m = lum - c / 2.0
        return onp.stack([r + m, g + m, b + m], axis=-1)

    def _apply_color_jitter(self, batch, draws):
        """contrast -> illumination -> HSL jitter -> PCA noise on a raw
        (N, 3, H, W) 0..255 batch (reference image_aug_default.cc
        DefaultImageAugmenter order; HSL ranges in OpenCV-HLS units:
        H 0..180 half-degrees, S/L 0..255).  The per-row parameters
        come pre-drawn in ``draws`` (plan-position aligned)."""
        if "contrast" in draws:
            batch = batch * draws["contrast"][:, None, None, None]
        if "illum" in draws:
            batch = batch + draws["illum"][:, None, None, None]
        if self._random_h or self._random_s or self._random_l:
            img = onp.clip(batch, 0, 255).transpose(0, 2, 3, 1) / 255.0
            hue, sat, lum = self._rgb_to_hsl(img)
            if "dh" in draws:
                hue = hue + 2.0 * draws["dh"][:, None, None]  # ->deg
            if "ds" in draws:
                sat = onp.clip(sat + draws["ds"][:, None, None]
                               / 255.0, 0.0, 1.0)
            if "dl" in draws:
                lum = onp.clip(lum + draws["dl"][:, None, None]
                               / 255.0, 0.0, 1.0)
            batch = (self._hsl_to_rgb(hue, sat, lum) * 255.0) \
                .transpose(0, 3, 1, 2).astype("float32")
        if "pca" in draws:
            shift = (draws["pca"] * self._PCA_EIGVAL) \
                @ self._PCA_EIGVEC.T
            batch = batch + shift[:, :, None, None]
        return onp.clip(batch, 0.0, 255.0)

    # --------------------------------------------------- worker pool
    def _start_pool(self, q, stop, plan):
        cv = threading.Condition()
        # bounded working state: "todo" is a heap of pending seqs,
        # "running" holds only in-flight claims (<= workers + a few
        # re-dispatches), and "plan" entries are pruned once emitted —
        # per-claim cost stays O(log batches), not O(batches)
        state = {"plan": {seq: (idx, take) for seq, idx, take in plan},
                 "todo": [seq for seq, _, _ in plan],
                 "running": {}, "results": {}, "next_emit": 0,
                 "poisoned": set(), "buried": set(), "charged": set(),
                 "aborts": {}, "fatal": None, "finished": False,
                 "last_progress": time.monotonic(),
                 "window": max(self._prefetch, 2 * self._io_workers)}
        heapq.heapify(state["todo"])
        self._pool = (state, cv)
        self._pool_threads = []
        for _ in range(self._io_workers):
            self._spawn_worker(state, cv, stop)
        self._emitter = threading.Thread(
            target=self._pool_emitter,
            args=(state, cv, stop, q, plan,
                  _tracing.current_context()),
            name="ImageRecordIter-emitter", daemon=True)
        self._emitter.start()

    def _spawn_worker(self, state, cv, stop):
        # data workers are THREADS: trace context propagates by
        # capture-at-spawn (tracing's stack is thread-local), not by
        # env stamp — a respawned worker inherits the respawner's
        # context so its records stay on the fit's causal timeline
        t = threading.Thread(target=self._pool_worker,
                             args=(state, cv, stop,
                                   _tracing.current_context()),
                             name="ImageRecordIter-worker", daemon=True)
        self._pool_threads.append(t)
        t.start()
        return t

    def _pool_worker(self, state, cv, stop, trace_ctx=None):
        from ..resilience import faultsim

        if trace_ctx is not None:
            # thread-lifetime bind: the TLS stack dies with the thread
            _tracing.use(trace_ctx).__enter__()
        me = threading.current_thread()
        while not stop.is_set():
            with cv:
                if state["finished"] or me in state["poisoned"]:
                    return
                seq = None
                if state["todo"] and state["todo"][0] \
                        < state["next_emit"] + state["window"]:
                    seq = heapq.heappop(state["todo"])
                    task = state["plan"].get(seq)
                    if task is None:  # stale re-dispatch of an
                        continue      # already-emitted batch
                    state["running"][seq] = {
                        "worker": me, "claimed_at": time.monotonic()}
                if seq is None:
                    cv.wait(0.1)
                    continue
            idx, take = task
            # probe, not inject: an io.worker 'crash' must kill THIS
            # worker (the SIGKILL analog the pool survives), never the
            # training process; 'delay' (slept inside probe) is the
            # straggler the per-batch deadline re-dispatches around
            act = faultsim.probe("io.worker")
            if act == "crash":
                return  # sudden death, batch held — emitter detects
            if act == "raise":
                # one aborted claim, absorbed: hand the batch back —
                # but BOUNDED per batch, or an open-ended raise spec
                # (io.worker:raise@1+) would re-dispatch forever and
                # hang the consumer instead of failing loudly
                with cv:
                    ent = state["running"].get(seq)
                    if ent is not None and ent["worker"] is me:
                        state["running"].pop(seq)
                        n_ab = state["aborts"].get(seq, 0) + 1
                        state["aborts"][seq] = n_ab
                        if n_ab > self._respawn_budget + 2:
                            state["results"].setdefault(
                                seq, ("fatal", MXNetError(
                                    f"io worker claim for batch {seq} "
                                    f"aborted {n_ab} times — refusing "
                                    f"to spin.  Quarantine manifest: "
                                    f"{self._manifest_path}")))
                        else:
                            heapq.heappush(state["todo"], seq)
                    cv.notify_all()
                continue
            try:
                payload = self._assemble(seq, idx, take)
                item = ("ok", payload)
            except Exception as exc:
                item = ("fatal", exc)
            recovered = False
            with cv:
                # first result wins: a re-dispatched twin computes the
                # identical payload, so dropping the loser is lossless
                # (a twin of an ALREADY-emitted seq is discarded — the
                # results dict must not accumulate dead entries)
                accepted = False
                if seq >= state["next_emit"]:
                    stored = state["results"].setdefault(seq, item)
                    accepted = stored is item
                    if accepted:
                        state["last_progress"] = time.monotonic()
                ent = state["running"].get(seq)
                if ent is not None and ent["worker"] is me:
                    state["running"].pop(seq)
                if accepted and me in state["poisoned"]:
                    # it delivered: slow, not dead — refund the
                    # replacement charge so a healthy-but-slow
                    # pipeline can never burn the death budget; rejoin
                    # the pool ONLY if it is below its configured size
                    # (the replacement otherwise carries on and this
                    # worker retires — the pool must not grow)
                    if me in state["charged"]:
                        state["charged"].discard(me)
                        self._respawn_charges = max(
                            0, self._respawn_charges - 1)
                    others = sum(
                        1 for t in self._pool_threads
                        if t.is_alive() and t is not me
                        and t not in state["poisoned"])
                    if others < self._io_workers:
                        state["poisoned"].discard(me)
                    recovered = True
                cv.notify_all()
            if recovered:
                from .. import telemetry

                telemetry.event("io_worker_recovered", seq=int(seq),
                                worker=me.name)

    def _police_pool(self, state, cv, stop):
        """Called under ``cv`` by the emitter: detect dead or wedged
        workers, re-dispatch the batches they hold, and respawn under
        the MXNET_IO_WORKER_RESPAWN budget.  Budget exhaustion is a
        loud structured failure carrying the quarantine manifest."""
        now = time.monotonic()
        needs_respawn = 0
        for seq in list(state["running"]):
            ent = state["running"][seq]
            w = ent["worker"]
            dead = not w.is_alive()
            wedged = now - ent["claimed_at"] > self._worker_deadline
            if not dead and not wedged:
                continue
            state["running"].pop(seq)
            if seq < state["next_emit"] or seq in state["results"]:
                # its batch is already covered (emitted, or a twin
                # delivered): nothing is lost, so a merely-slow worker
                # here must not be poisoned or charged — only reap the
                # stale claim (a DEAD one still counts: it can never
                # claim again, so the pool genuinely shrank)
                if dead and w not in state["buried"]:
                    state["buried"].add(w)
                    needs_respawn += 1
                continue
            heapq.heappush(state["todo"], seq)
            if dead:
                if w not in state["buried"]:
                    state["buried"].add(w)
                    needs_respawn += 1
            else:
                # wedged but alive: poison it (no new claims; a late
                # result is still accepted first-wins, un-poisoning it
                # and refunding the charge) and replace it
                if w not in state["poisoned"]:
                    state["poisoned"].add(w)
                    state["charged"].add(w)
                    needs_respawn += 1
            from .. import telemetry

            telemetry.event("io_worker_lost", seq=int(seq),
                            dead=bool(dead),
                            worker=getattr(w, "name", None))
        # all workers gone with work left: also a respawn case (covers
        # a crash wave that emptied the pool between claims)
        alive = [t for t in self._pool_threads
                 if t.is_alive() and t not in state["poisoned"]]
        work_left = bool(state["todo"]) or bool(state["running"])
        if not alive and work_left and not needs_respawn:
            needs_respawn = 1
        # never grow the pool past its configured size: with enough
        # healthy workers left, the re-dispatch alone is the recovery
        needs_respawn = min(needs_respawn,
                            max(0, self._io_workers - len(alive)))
        for _ in range(needs_respawn):
            if self._respawn_charges >= self._respawn_budget:
                # soft exhaustion first: poisoned-but-alive workers
                # may still DELIVER (slow is not dead — an accepted
                # late result refunds its charge).  Fatal only when
                # nothing is alive, or nothing has progressed for a
                # full stall window — bounded, never a hang
                alive_any = any(t.is_alive()
                                for t in self._pool_threads)
                stall = time.monotonic() - state["last_progress"]
                grace = max(2.0 * self._worker_deadline, 1.0)
                if alive_any and stall <= grace:
                    return  # hold: a late delivery may free budget
                self._flush_manifest()  # the error names it
                state["fatal"] = MXNetError(
                    f"io worker respawn budget exhausted "
                    f"({self._respawn_budget}) with no pool progress "
                    f"for {stall:.1f}s — the decode pool keeps dying "
                    f"or is wedged; refusing to continue.  Quarantine "
                    f"manifest: {self._manifest_path}")
                cv.notify_all()
                return
            self._respawns += 1
            self._respawn_charges += 1
            self._spawn_worker(state, cv, stop)
            from .. import telemetry

            telemetry.count("io_worker_respawns")
            rl = telemetry.current()
            if rl is not None:
                rl.data_plane("respawn", workers=self._io_workers,
                              respawn=self._respawns,
                              budget=self._respawn_budget)

    def _pool_emitter(self, state, cv, stop, q, plan, trace_ctx=None):
        """Emit results strictly in plan order (sequence-ordered batch
        assembly): the consumer sees the same stream at any worker
        count."""
        if trace_ctx is not None:
            # thread-lifetime bind (matches _pool_worker): respawn
            # records the emitter writes stay on the caller's trace
            _tracing.use(trace_ctx).__enter__()
        n = len(plan)
        try:
            while not stop.is_set() and state["next_emit"] < n:
                with cv:
                    seq = state["next_emit"]
                    item = state["results"].pop(seq, None)
                    if item is None:
                        if state["fatal"] is not None:
                            item = ("fatal", state["fatal"])
                        else:
                            cv.wait(0.1)
                            self._police_pool(state, cv, stop)
                            continue
                    else:
                        state["plan"].pop(seq, None)  # prune: emitted
                        state["next_emit"] = seq + 1
                        cv.notify_all()
                if item[0] == "fatal":
                    with cv:
                        state["finished"] = True
                        cv.notify_all()
                    self._flush_manifest()
                    self._put(q, stop, ("error", item[1]))
                    return
                batch, lab_arr, pad_out = item[1]
                if self._device_feed:
                    ok = self._put(q, stop,
                                   ("ready",
                                    self._emit(batch, lab_arr,
                                               pad_out)))
                else:
                    ok = self._put(q, stop, (batch, lab_arr, pad_out))
                if not ok:
                    return
            if not stop.is_set():
                self._flush_manifest()  # epoch end: debounced rows
                self._put(q, stop, None)
        except Exception as exc:
            self._flush_manifest()
            if not stop.is_set():
                self._put(q, stop, ("error", exc))
        finally:
            with cv:
                state["finished"] = True
                cv.notify_all()

    # ---------------------------------------------------------- iterator
    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         self._dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape, "float32")]

    def _stop_pipeline(self):
        """Stop producer/pool threads with bounded joins: puts are
        stop-aware, so every thread exits within one put timeout of
        the stop event even against a consumer that never drained."""
        self._stop.set()
        if self._pool is not None:
            _, cv = self._pool
            with cv:
                cv.notify_all()
        threads = [t for t in ([self._worker, self._emitter]
                               + self._pool_threads) if t is not None]
        if not threads:
            return
        from .. import config as _config

        budget = float(_config.get_env("MXNET_FEED_JOIN_TIMEOUT_SEC"))
        deadline = time.monotonic() + budget
        for t in threads:
            while t.is_alive() and time.monotonic() < deadline:
                if self._queue is not None:
                    try:
                        while True:
                            self._queue.get_nowait()
                    except queue.Empty:
                        pass
                t.join(timeout=0.1)
            if t.is_alive():
                import logging

                logging.warning(
                    "ImageRecordIter: %s did not join within %.1fs; "
                    "abandoning daemon thread", t.name, budget)
        self._worker = None
        self._emitter = None
        self._pool_threads = []
        self._pool = None

    def reset(self):
        self._stop_pipeline()
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._epoch += 1
        self._stop = threading.Event()
        self._done = False
        self._queue = queue.Queue(maxsize=self._prefetch)
        self._plan = self._build_plan()
        if self._io_workers > 0:
            self._start_pool(self._queue, self._stop, self._plan)
        else:
            self._worker = threading.Thread(
                target=self._producer,
                args=(self._queue, self._stop, self._plan),
                name="ImageRecordIter-producer", daemon=True)
            self._worker.start()

    def next(self):
        if self._done:  # exhausted epoch: don't block on a dead producer
            raise StopIteration
        item = self._queue.get()
        if item is None:
            self._done = True
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "error":
            self._done = True
            raise item[1]
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "ready":  # device-feed: already on device
            return item[1]
        batch, labels, pad = item
        return self._emit(batch, labels, pad)

    def close(self):
        self._stop_pipeline()
        self._flush_manifest()  # a killed epoch still names its skips
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        self._records = None  # release memoryviews into the mmap
        try:
            self._mm.close()
        except BufferError:
            # an abandoned (join-timed-out) worker still holds a view
            # into the mmap: leave it to the GC rather than raise out
            # of close() — the stop event keeps the thread from ever
            # touching the queue again
            import logging

            logging.warning("ImageRecordIter: mmap still referenced "
                            "by an abandoned worker; deferring close")
        self._file.close()


class ImageDetRecordIter(ImageRecordIter):
    """Detection RecordIO iterator (reference
    src/io/iter_image_det_recordio.cc:597).

    Record label layout (the im2rec detection convention): a flat float
    vector ``[header_width, object_width, <extra header...>,
    obj0(object_width values: id, xmin, ymin, xmax, ymax, ...), ...]``
    with normalized corner coordinates.  Batches emit labels shaped
    (batch, max_objects, object_width) padded with -1 — what
    MultiBoxTarget consumes.

    Augmentation is bbox-aware: images are plain-resized to data_shape
    (no crop — the reference's det-crop sampler with min_object_covered
    is out of scope this round) and ``rand_mirror`` flips the image AND
    remaps [xmin, xmax] -> [1-xmax, 1-xmin].
    """

    _defer_start = True  # producer starts after det config is set
    _label_fill_value = -1.0  # "no object" (MultiBoxTarget contract)

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_pad_width=0, object_width=5, shuffle=False,
                 rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, label_width=-1,
                 round_batch=True, part_index=0, num_parts=1, seed=0,
                 dtype="float32", **kwargs):
        if kwargs.pop("rand_crop", None):
            raise MXNetError(
                "ImageDetRecordIter: rand_crop is not bbox-aware yet; "
                "use rand_mirror")
        super().__init__(
            path_imgrec, data_shape, batch_size, shuffle=shuffle,
            rand_crop=False, rand_mirror=False, resize=-1,
            mean_r=mean_r, mean_g=mean_g, mean_b=mean_b, std_r=std_r,
            std_g=std_g, std_b=std_b, label_width=1,
            round_batch=round_batch, part_index=part_index,
            num_parts=num_parts, seed=seed, dtype=dtype, **kwargs)
        self._det_mirror = rand_mirror
        self._object_width = int(object_width)
        if label_pad_width:
            self._max_objs = (int(label_pad_width) - 2) \
                // self._object_width
        else:
            self._max_objs = self._scan_max_objs()
        self.reset()  # start the producer (deferred in the base init)

    def _scan_max_objs(self):
        m = 1
        for rec in self._records:
            # header-only read: unpack slices, so passing the
            # memoryview avoids copying the JPEG payload; a record too
            # corrupt to unpack is skipped here and quarantined when a
            # batch first touches it
            try:
                header, _ = recordio.unpack(rec)
            except Exception:
                continue
            lab = onp.atleast_1d(onp.asarray(header.label, "float32"))
            if lab.size >= 2:
                ow = int(lab[1])
                hw = int(lab[0])
                m = max(m, (lab.size - hw) // max(ow, 1))
        return m

    def _parse_det_label(self, lab):
        lab = onp.atleast_1d(onp.asarray(lab, "float32"))
        ow = self._object_width
        out = onp.full((self._max_objs, ow), -1.0, "float32")
        if lab.size < 2:
            return out
        hw = int(lab[0])
        rec_ow = max(int(lab[1]), 1)  # zero guard: malformed record
        objs = lab[hw:]
        nobj = min(objs.size // rec_ow, self._max_objs)
        for k in range(nobj):
            out[k, :min(ow, rec_ow)] = objs[k * rec_ow:
                                            k * rec_ow + min(ow, rec_ow)]
        return out

    def _make_batch(self, idx, rng):
        from .. import image as img_mod

        c, h, w = self.data_shape
        if c != 3:
            raise MXNetError(
                "ImageDetRecordIter decodes 3-channel images; "
                f"data_shape[0]={c}")
        mirror = ((rng.rand(len(idx)) < 0.5)
                  if self._det_mirror
                  else onp.zeros(len(idx), bool))
        rows, labs, kept = [], [], []
        for pos, j in enumerate(idx):
            j = int(j)
            payload = self._load_record(j)
            if payload is None:
                continue
            header, img = payload
            try:
                im = img_mod.imdecode(img)
                im = img_mod.imresize(im, w, h)
                arr = im.asnumpy().astype("float32")
            except Exception as exc:
                self._quarantine(j, "decode", exc)
                continue
            lab = self._parse_det_label(header.label)
            if mirror[pos]:
                arr = arr[:, ::-1]
                valid = lab[:, 0] >= 0
                xmin = lab[valid, 1].copy()
                xmax = lab[valid, 3].copy()
                lab[valid, 1] = 1.0 - xmax
                lab[valid, 3] = 1.0 - xmin
            arr = (arr - self._mean) / self._std
            rows.append(arr.transpose(2, 0, 1))
            labs.append(lab)
            kept.append(pos)
        if rows:
            batch = onp.stack(rows).astype("float32")
            labels = onp.stack(labs).astype("float32")
        else:
            batch = onp.zeros((0, c, h, w), "float32")
            labels = onp.full(
                (0, self._max_objs, self._object_width), -1.0,
                "float32")
        return batch, labels, kept

    @property
    def provide_label(self):
        return [DataDesc(
            "label",
            (self.batch_size, self._max_objs, self._object_width),
            "float32")]
