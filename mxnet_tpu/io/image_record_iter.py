"""ImageRecordIter — the high-throughput record→decode→augment→batch
pipeline.

Reference parity: src/io/iter_image_recordio_2.cc:880
(ImageRecordIter2: dmlc chunk reader → preprocess_threads decode+augment
workers → batch assembly → PrefetcherIter double buffering) and its
MXNET_REGISTER_IO_ITER("ImageRecordIter") python surface
(mx.io.ImageRecordIter kwargs).

TPU-native design: the whole .rec is memory-mapped and framed by the
native C++ parser; batches of JPEGs decode+augment in C++ worker
threads straight into NCHW float32 buffers (GIL released); a background
Python thread keeps ``prefetch_buffer`` batches ready so the
accelerator never waits on the host.  PIL fallback keeps functionality
without the native lib.
"""
from __future__ import annotations

import mmap
import queue
import threading

import numpy as onp

from .. import recordio
from ..base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageDetRecordIter", "ImageRecordIter"]


class ImageRecordIter(DataIter):
    """mx.io.ImageRecordIter (reference C++ iterator surface).

    Supported kwargs mirror the reference's ImageRecordParam /
    augmenter params: path_imgrec, data_shape, batch_size, shuffle,
    rand_crop, rand_mirror, resize, mean_r/g/b, std_r/g/b,
    preprocess_threads, prefetch_buffer, label_width, round_batch,
    part_index/num_parts (sharding), seed.
    """

    #: ImageNet PCA lighting basis (reference src/io/image_aug_default.cc
    #: — the AlexNet eigen decomposition, 0..255 pixel scale)
    _PCA_EIGVAL = onp.array([55.46, 4.794, 1.148], "float32")
    _PCA_EIGVEC = onp.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.8140],
                             [-0.5836, -0.6948, 0.4203]], "float32")

    def __init__(self, path_imgrec, data_shape, batch_size, shuffle=False,
                 rand_crop=False, rand_mirror=False, resize=-1,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0,
                 std_g=1.0, std_b=1.0, preprocess_threads=None,
                 prefetch_buffer=None, label_width=1, round_batch=True,
                 part_index=0, num_parts=1, seed=0, dtype="float32",
                 random_h=0, random_s=0, random_l=0, pca_noise=0.0,
                 max_random_contrast=0.0, max_random_illumination=0.0,
                 device_feed=None, **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (c, h, w)")
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._mean = onp.array([mean_r, mean_g, mean_b], "float32")
        self._std = onp.array([std_r, std_g, std_b], "float32")
        # color-space augmenters (reference image_aug_default.cc:565
        # RandomHueSaturationLight): HSL jitter ranges follow the
        # reference's OpenCV-HLS units (H 0..180, S/L 0..255)
        self._random_h = float(random_h)
        self._random_s = float(random_s)
        self._random_l = float(random_l)
        self._pca_noise = float(pca_noise)
        self._max_contrast = float(max_random_contrast)
        self._max_illumination = float(max_random_illumination)
        self._color_jitter = any((self._random_h, self._random_s,
                                  self._random_l, self._pca_noise,
                                  self._max_contrast,
                                  self._max_illumination))
        from .. import config as _config

        self._threads = (preprocess_threads if preprocess_threads
                         is not None
                         else _config.get_env("MXNET_CPU_WORKER_NTHREADS"))
        self._prefetch = (prefetch_buffer if prefetch_buffer is not None
                          else _config.get_env("MXNET_TPU_PREFETCH_BUFFER"))
        self._round_batch = round_batch
        self._rng = onp.random.RandomState(seed)
        self._dtype = dtype
        if device_feed is None:
            from .device_feed import device_feed_enabled

            device_feed = device_feed_enabled()
        # device feed: the producer thread builds the DEVICE batch
        # (nd.array = host->HBM device_put), so up to prefetch_buffer
        # batches sit HBM-resident while the consumer's step runs —
        # next() hands them over without a blocking transfer
        self._device_feed = bool(device_feed)

        # mmap + frame the record file once (host page cache does the
        # streaming; the reference reads chunks instead)
        self._file = open(path_imgrec, "rb")
        self._mm = mmap.mmap(self._file.fileno(), 0,
                             access=mmap.ACCESS_READ)
        from .. import _native

        if _native.get_lib() is not None:
            self._records = _native.parse_records(self._mm)
        else:
            self._records = self._parse_python()
        if num_parts > 1:
            self._records = self._records[part_index::num_parts]
        if not self._records:
            raise MXNetError(f"no records in {path_imgrec}")
        self._order = onp.arange(len(self._records))
        self._queue = None
        self._worker = None
        self._stop = threading.Event()
        if not getattr(self, "_defer_start", False):
            # subclasses with extra config (ImageDetRecordIter) start
            # the producer themselves once fully constructed
            self.reset()

    def _parse_python(self):
        # pure-python fallback: ONE source of framing truth —
        # MXRecordIO.read (continuation reassembly, truncation checks)
        records = []
        reader = recordio.MXRecordIO(self._file.name, "r")
        try:
            while True:
                rec = reader.read()
                if rec is None:
                    break
                records.append(memoryview(rec))
        finally:
            reader.close()
        return records

    # ----------------------------------------------------------- pipeline
    def _producer(self):
        try:
            self._producer_impl()
        except Exception as exc:  # surface in next(), don't hang it
            if not self._stop.is_set():
                self._queue.put(("error", exc))

    def _producer_impl(self):
        bs = self.batch_size
        order = self._order
        n = len(order)
        i = 0
        while not self._stop.is_set() and i < n:
            take = min(bs, n - i)
            idx = order[i:i + take]
            i += take
            pad = bs - take
            if pad and self._round_batch:
                # wrap around to fill, report pad; onp.resize cycles
                # when the dataset/shard is smaller than a batch
                idx = onp.concatenate([idx, onp.resize(order, pad)])
            # round_batch=False: final batch is genuinely smaller, pad=0
            batch, lab_arr = self._make_batch(idx)
            if self._stop.is_set():
                break
            pad_out = pad if self._round_batch else 0
            if self._device_feed:
                self._queue.put(("ready",
                                 self._emit(batch, lab_arr, pad_out)))
            else:
                self._queue.put((batch, lab_arr, pad_out))
        if not self._stop.is_set():
            self._queue.put(None)

    def _emit(self, batch, labels, pad):
        """numpy batch -> DataBatch of device NDArrays; in device-feed
        mode this runs in the PRODUCER thread so the H2D transfer
        overlaps the consumer's running step."""
        from .. import ndarray as nd

        data = nd.array(batch.astype(self._dtype)
                        if self._dtype != "float32" else batch,
                        dtype=self._dtype)
        lab = nd.array(labels[:, 0]
                       if (self.label_width == 1 and labels.ndim == 2)
                       else labels)
        return DataBatch(data=[data], label=[lab], pad=pad)

    def _make_batch(self, idx):
        """Decode+augment one index batch; subclasses override for
        different label/augment semantics (ImageDetRecordIter)."""
        c, h, w = self.data_shape
        out_rows = len(idx)
        jpegs, labels = [], []
        for j in idx:
            header, img = recordio.unpack(bytes(self._records[j]))
            jpegs.append(img)
            lab = onp.atleast_1d(onp.asarray(header.label, "float32"))
            labels.append(lab[:self.label_width])
        batch = self._decode_batch(jpegs, h, w)
        lab_arr = onp.zeros((out_rows, self.label_width), "float32")
        for k, lab in enumerate(labels):
            lab_arr[k, :len(lab)] = lab
        return batch, lab_arr

    def _decode_batch(self, jpegs, h, w):
        from .. import _native

        nimg = len(jpegs)
        crop_x = (self._rng.rand(nimg).astype("float32")
                  if self._rand_crop else onp.full(nimg, 0.5, "float32"))
        crop_y = (self._rng.rand(nimg).astype("float32")
                  if self._rand_crop else onp.full(nimg, 0.5, "float32"))
        mirror = ((self._rng.rand(nimg) < 0.5).astype("uint8")
                  if self._rand_mirror
                  else onp.zeros(nimg, "uint8"))
        if _native.get_lib() is not None:
            if self._color_jitter:
                # decode raw 0..255 (native normalization off), jitter
                # in color space, then normalize here — the reference
                # default-aug chain orders it the same way
                # (image_aug_default.cc: hsl/pca before mean subtract)
                raw, _ = _native.decode_augment_batch(
                    jpegs, h, w,
                    mean=onp.zeros(3, "float32"),
                    std=onp.ones(3, "float32"),
                    crop_x=crop_x, crop_y=crop_y, mirror=mirror,
                    resize_short=self._resize,
                    num_threads=self._threads)
                raw = self._apply_color_jitter(raw)
                return ((raw - self._mean[None, :, None, None])
                        / self._std[None, :, None, None])
            batch, _ = _native.decode_augment_batch(
                jpegs, h, w, mean=self._mean, std=self._std,
                crop_x=crop_x, crop_y=crop_y, mirror=mirror,
                resize_short=self._resize, num_threads=self._threads)
            return batch
        # PIL fallback (slow path, functional parity)
        from .. import image as img_mod
        from .. import ndarray as nd

        out = onp.zeros((nimg, 3, h, w), "float32")
        for k, j in enumerate(jpegs):
            im = img_mod.imdecode(j)
            if self._resize > 0:
                im = img_mod.resize_short(im, self._resize)
            ih, iw = im.shape[:2]
            if ih >= h and iw >= w:
                x0 = int(crop_x[k] * (iw - w))
                y0 = int(crop_y[k] * (ih - h))
                im = img_mod.fixed_crop(im, x0, y0, w, h)
            else:
                im = img_mod.imresize(im, w, h)
            arr = im.asnumpy().astype("float32")
            if mirror[k]:
                arr = arr[:, ::-1]
            if not self._color_jitter:
                arr = (arr - self._mean) / self._std
            out[k] = arr.transpose(2, 0, 1)
        if self._color_jitter:
            out = self._apply_color_jitter(out)
            out = ((out - self._mean[None, :, None, None])
                   / self._std[None, :, None, None])
        return out

    # ------------------------------------------- color-space augmenters
    @staticmethod
    def _rgb_to_hsl(rgb):
        """Vectorized RGB(0..1) -> (H deg 0..360, S 0..1, L 0..1)."""
        r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
        maxc = onp.max(rgb, axis=-1)
        minc = onp.min(rgb, axis=-1)
        delta = maxc - minc
        lum = (maxc + minc) / 2.0
        denom = 1.0 - onp.abs(2.0 * lum - 1.0)
        sat = onp.where(delta > 0, delta / onp.maximum(denom, 1e-12), 0.0)
        safe = onp.maximum(delta, 1e-12)
        hr = onp.where(maxc == r, ((g - b) / safe) % 6.0, 0.0)
        hg = onp.where(maxc == g, (b - r) / safe + 2.0, 0.0)
        hb = onp.where(maxc == b, (r - g) / safe + 4.0, 0.0)
        # priority r > g > b on ties, like colorsys
        hue = onp.where(maxc == r, hr, onp.where(maxc == g, hg, hb))
        hue = onp.where(delta > 0, hue * 60.0, 0.0)
        return hue, sat, lum

    @staticmethod
    def _hsl_to_rgb(hue, sat, lum):
        c = (1.0 - onp.abs(2.0 * lum - 1.0)) * sat
        hp = (hue % 360.0) / 60.0
        x = c * (1.0 - onp.abs(hp % 2.0 - 1.0))
        z = onp.zeros_like(c)
        conds = [(hp < 1), (hp < 2), (hp < 3), (hp < 4), (hp < 5)]
        r = onp.select(conds, [c, x, z, z, x], c)
        g = onp.select(conds, [x, c, c, x, z], z)
        b = onp.select(conds, [z, z, x, c, c], x)
        m = lum - c / 2.0
        return onp.stack([r + m, g + m, b + m], axis=-1)

    def _apply_color_jitter(self, batch):
        """contrast -> illumination -> HSL jitter -> PCA noise on a raw
        (N, 3, H, W) 0..255 batch (reference image_aug_default.cc
        DefaultImageAugmenter order; HSL ranges in OpenCV-HLS units:
        H 0..180 half-degrees, S/L 0..255)."""
        n = batch.shape[0]
        rng = self._rng
        if self._max_contrast > 0:
            alpha = 1.0 + rng.uniform(-self._max_contrast,
                                      self._max_contrast, n)
            batch = batch * alpha[:, None, None, None].astype("float32")
        if self._max_illumination > 0:
            beta = rng.uniform(-self._max_illumination,
                               self._max_illumination, n)
            batch = batch + beta[:, None, None, None].astype("float32")
        if self._random_h or self._random_s or self._random_l:
            img = onp.clip(batch, 0, 255).transpose(0, 2, 3, 1) / 255.0
            hue, sat, lum = self._rgb_to_hsl(img)
            if self._random_h:
                dh = rng.uniform(-self._random_h, self._random_h, n)
                hue = hue + 2.0 * dh[:, None, None]  # half-deg -> deg
            if self._random_s:
                ds = rng.uniform(-self._random_s, self._random_s, n)
                sat = onp.clip(sat + ds[:, None, None] / 255.0, 0.0, 1.0)
            if self._random_l:
                dl = rng.uniform(-self._random_l, self._random_l, n)
                lum = onp.clip(lum + dl[:, None, None] / 255.0, 0.0, 1.0)
            batch = (self._hsl_to_rgb(hue, sat, lum) * 255.0) \
                .transpose(0, 3, 1, 2).astype("float32")
        if self._pca_noise > 0:
            alpha = rng.normal(0.0, self._pca_noise, (n, 3)) \
                .astype("float32")
            shift = (alpha * self._PCA_EIGVAL) @ self._PCA_EIGVEC.T
            batch = batch + shift[:, :, None, None]
        return onp.clip(batch, 0.0, 255.0)

    # ---------------------------------------------------------- iterator
    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         self._dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape, "float32")]

    def reset(self):
        self._stop.set()
        if self._worker is not None:
            # drain so the producer can observe the stop event
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._worker.join()
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._stop = threading.Event()
        self._done = False
        self._queue = queue.Queue(maxsize=self._prefetch)
        self._worker = threading.Thread(target=self._producer,
                                        daemon=True)
        self._worker.start()

    def next(self):
        if self._done:  # exhausted epoch: don't block on a dead producer
            raise StopIteration
        item = self._queue.get()
        if item is None:
            self._done = True
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "error":
            self._done = True
            raise item[1]
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "ready":  # device-feed: already on device
            return item[1]
        batch, labels, pad = item
        return self._emit(batch, labels, pad)

    def close(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._worker is not None:
            self._worker.join()
        self._records = None  # release memoryviews into the mmap
        self._mm.close()
        self._file.close()


class ImageDetRecordIter(ImageRecordIter):
    """Detection RecordIO iterator (reference
    src/io/iter_image_det_recordio.cc:597).

    Record label layout (the im2rec detection convention): a flat float
    vector ``[header_width, object_width, <extra header...>,
    obj0(object_width values: id, xmin, ymin, xmax, ymax, ...), ...]``
    with normalized corner coordinates.  Batches emit labels shaped
    (batch, max_objects, object_width) padded with -1 — what
    MultiBoxTarget consumes.

    Augmentation is bbox-aware: images are plain-resized to data_shape
    (no crop — the reference's det-crop sampler with min_object_covered
    is out of scope this round) and ``rand_mirror`` flips the image AND
    remaps [xmin, xmax] -> [1-xmax, 1-xmin].
    """

    _defer_start = True  # producer starts after det config is set

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_pad_width=0, object_width=5, shuffle=False,
                 rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, label_width=-1,
                 round_batch=True, part_index=0, num_parts=1, seed=0,
                 dtype="float32", **kwargs):
        if kwargs.get("rand_crop"):
            raise MXNetError(
                "ImageDetRecordIter: rand_crop is not bbox-aware yet; "
                "use rand_mirror")
        super().__init__(
            path_imgrec, data_shape, batch_size, shuffle=shuffle,
            rand_crop=False, rand_mirror=False, resize=-1,
            mean_r=mean_r, mean_g=mean_g, mean_b=mean_b, std_r=std_r,
            std_g=std_g, std_b=std_b, label_width=1,
            round_batch=round_batch, part_index=part_index,
            num_parts=num_parts, seed=seed, dtype=dtype)
        self._det_mirror = rand_mirror
        self._object_width = int(object_width)
        if label_pad_width:
            self._max_objs = (int(label_pad_width) - 2) \
                // self._object_width
        else:
            self._max_objs = self._scan_max_objs()
        self.reset()  # start the producer (deferred in the base init)

    def _scan_max_objs(self):
        m = 1
        for rec in self._records:
            # header-only read: unpack slices, so passing the
            # memoryview avoids copying the JPEG payload
            header, _ = recordio.unpack(rec)
            lab = onp.atleast_1d(onp.asarray(header.label, "float32"))
            if lab.size >= 2:
                ow = int(lab[1])
                hw = int(lab[0])
                m = max(m, (lab.size - hw) // max(ow, 1))
        return m

    def _parse_det_label(self, lab):
        lab = onp.atleast_1d(onp.asarray(lab, "float32"))
        ow = self._object_width
        out = onp.full((self._max_objs, ow), -1.0, "float32")
        if lab.size < 2:
            return out
        hw = int(lab[0])
        rec_ow = max(int(lab[1]), 1)  # zero guard: malformed record
        objs = lab[hw:]
        nobj = min(objs.size // rec_ow, self._max_objs)
        for k in range(nobj):
            out[k, :min(ow, rec_ow)] = objs[k * rec_ow:
                                            k * rec_ow + min(ow, rec_ow)]
        return out

    def _make_batch(self, idx):
        from .. import image as img_mod

        c, h, w = self.data_shape
        if c != 3:
            raise MXNetError(
                "ImageDetRecordIter decodes 3-channel images; "
                f"data_shape[0]={c}")
        out_rows = len(idx)
        batch = onp.zeros((out_rows, c, h, w), "float32")
        labels = onp.full(
            (out_rows, self._max_objs, self._object_width), -1.0,
            "float32")
        mirror = ((self._rng.rand(out_rows) < 0.5)
                  if self._det_mirror
                  else onp.zeros(out_rows, bool))
        for k, j in enumerate(idx):
            header, img = recordio.unpack(bytes(self._records[j]))
            im = img_mod.imdecode(img)
            im = img_mod.imresize(im, w, h)
            arr = im.asnumpy().astype("float32")
            lab = self._parse_det_label(header.label)
            if mirror[k]:
                arr = arr[:, ::-1]
                valid = lab[:, 0] >= 0
                xmin = lab[valid, 1].copy()
                xmax = lab[valid, 3].copy()
                lab[valid, 1] = 1.0 - xmax
                lab[valid, 3] = 1.0 - xmin
            arr = (arr - self._mean) / self._std
            batch[k] = arr.transpose(2, 0, 1)
            labels[k] = lab
        return batch, labels

    @property
    def provide_label(self):
        return [DataDesc(
            "label",
            (self.batch_size, self._max_objs, self._object_width),
            "float32")]
