"""Non-image C++ data iterators, TPU-native re-implementations.

Reference parity: src/io/iter_csv.cc:218 (CSVIter), iter_libsvm.cc
(LibSVMIter), iter_mnist.cc:260 (MNISTIter).  The reference implements
these as threaded C++ parser iterators; here parsing is one vectorized
numpy pass at construction (host RAM holds the parsed tensor; batches
are O(1) slices — the dataset sizes these iterators serve fit easily,
and the TPU feed path wants large contiguous host buffers anyway).
"""
from __future__ import annotations

import gzip
import struct

import numpy as onp

from .. import ndarray as nd
from ..base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["CSVIter", "LibSVMIter", "MNISTIter"]


class _ArrayFeedIter(DataIter):
    """Shared batching engine: dense arrays in, reference round_batch /
    pad semantics out."""

    def __init__(self, data, label, batch_size, shuffle=False,
                 round_batch=True, seed=0, data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self._data = data
        self._label = label
        self._shuffle = shuffle
        self._round_batch = round_batch
        self._rng = onp.random.RandomState(seed)
        self._order = onp.arange(len(data))
        self._data_name = data_name
        self._label_name = label_name
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self._data.shape[1:])]

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size,) + self._label.shape[1:])]

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            self._rng.shuffle(self._order)

    def iter_next(self):
        return self._cursor < len(self._order)

    def next(self):
        if not self.iter_next():
            raise StopIteration
        n = len(self._order)
        end = self._cursor + self.batch_size
        idx = self._order[self._cursor:end]
        pad = 0
        if end > n:
            if self._round_batch:
                # wrap and report pad; onp.resize cycles when the
                # dataset is smaller than the remaining pad (same
                # semantics as ImageRecordIter)
                pad = end - n
                idx = onp.concatenate([idx, onp.resize(self._order, pad)])
            # round_batch=False: final batch genuinely smaller, pad=0
        self._cursor = end
        return DataBatch(
            data=[nd.array(self._data[idx])],
            label=[nd.array(self._label[idx])],
            pad=pad, index=idx,
            provide_data=self.provide_data,
            provide_label=self.provide_label)


class CSVIter(_ArrayFeedIter):
    """Reference: src/io/iter_csv.cc:218 — dense CSV rows reshaped to
    ``data_shape``; optional label CSV (default 0s, reference
    behavior)."""

    def __init__(self, data_csv, data_shape, batch_size, label_csv=None,
                 label_shape=(1,), shuffle=False, round_batch=True,
                 seed=0, dtype="float32", **kwargs):
        raw = onp.loadtxt(data_csv, delimiter=",", dtype=dtype,
                          ndmin=2)
        want = 1
        for d in data_shape:
            want *= int(d)
        if raw.shape[1] != want:
            raise MXNetError(
                f"CSVIter: {raw.shape[1]} columns cannot reshape to "
                f"data_shape {tuple(data_shape)}")
        data = raw.reshape((-1,) + tuple(int(d) for d in data_shape))
        if label_csv is not None:
            lab = onp.loadtxt(label_csv, delimiter=",", dtype=dtype,
                              ndmin=2)
            lab = lab.reshape((-1,) + tuple(int(d) for d in label_shape))
            if len(lab) != len(data):
                raise MXNetError("CSVIter: label/data row mismatch")
        else:
            lab = onp.zeros((len(data),) + tuple(
                int(d) for d in label_shape), dtype)
        if tuple(label_shape) == (1,):
            lab = lab.reshape(len(data))
        super().__init__(data, lab, batch_size, shuffle, round_batch,
                         seed)


class LibSVMIter(_ArrayFeedIter):
    """Reference: src/io/iter_libsvm.cc — ``label idx:val ...`` rows.

    Returns DENSE batches of width ``data_shape[0]`` (SURVEY §7: sparse
    compute is TPU-hostile; the dense-backed row is what the model
    consumes anyway)."""

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_shape=(1,), shuffle=False, round_batch=True,
                 seed=0, dtype="float32", **kwargs):
        width = int(data_shape[0]) if isinstance(
            data_shape, (tuple, list)) else int(data_shape)
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                row = onp.zeros(width, dtype)
                for tok in parts[1:]:
                    k, v = tok.split(":")
                    k = int(k)
                    if k >= width:
                        raise MXNetError(
                            f"LibSVMIter: index {k} >= data_shape "
                            f"{width}")
                    row[k] = float(v)
                rows.append(row)
        data = onp.stack(rows) if rows else onp.zeros((0, width), dtype)
        super().__init__(data, onp.asarray(labels, dtype), batch_size,
                         shuffle, round_batch, seed)


def _read_idx(path):
    """Parse an IDX (MNIST) file, gzip-transparent."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        raw = f.read()
    magic, = struct.unpack(">i", raw[:4])
    ndim = magic & 0xFF
    dtype_code = (magic >> 8) & 0xFF
    if dtype_code != 0x08:
        raise MXNetError(f"IDX dtype {dtype_code:#x} unsupported")
    dims = struct.unpack(">" + "i" * ndim, raw[4:4 + 4 * ndim])
    a = onp.frombuffer(raw, dtype=onp.uint8, offset=4 + 4 * ndim)
    return a.reshape(dims)


class MNISTIter(_ArrayFeedIter):
    """Reference: src/io/iter_mnist.cc:260 — IDX image/label files,
    pixel scaling to [0,1], optional flat output."""

    def __init__(self, image, label, batch_size=128, shuffle=False,
                 flat=False, seed=0, silent=True, input_shape=None,
                 **kwargs):
        imgs = _read_idx(image).astype("float32") / 255.0
        labs = _read_idx(label).astype("float32")
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        elif input_shape is not None:
            imgs = imgs.reshape((len(imgs),) + tuple(input_shape))
        else:
            imgs = imgs[:, None]  # (N, 1, 28, 28)
        if len(imgs) != len(labs):
            raise MXNetError("MNISTIter: image/label count mismatch")
        super().__init__(imgs, labs, batch_size, shuffle, True, seed)
