"""Data iterators (reference: python/mxnet/io/)."""
from .io import *  # noqa: F401,F403
from .device_feed import (  # noqa: F401
    DeviceFeedIter, as_device_batch, device_feed_enabled)
from .image_record_iter import (  # noqa: F401
    ImageDetRecordIter, ImageRecordIter)
from .iterators import CSVIter, LibSVMIter, MNISTIter  # noqa: F401
