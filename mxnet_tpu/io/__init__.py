"""Data iterators (reference: python/mxnet/io/)."""
from .io import *  # noqa: F401,F403
