"""Data iterators (reference: python/mxnet/io/)."""
from .io import *  # noqa: F401,F403
from .image_record_iter import ImageRecordIter  # noqa: F401
