"""DataIter / NDArrayIter / ResizeIter / PrefetchingIter.

Reference parity: python/mxnet/io/io.py (``DataIter`` base, ``NDArrayIter``
:491 in-memory iterator with shuffle + last_batch_handling, ``ResizeIter``
:282, ``PrefetchingIter`` :347) and ``DataDesc``/``DataBatch``.

TPU-native notes: batches are assembled on host numpy and device_put once
per batch; the heavy ImageRecord pipeline lives in ``mxnet_tpu.recordio``
and image modules.
"""
from __future__ import annotations

import threading
from collections import namedtuple

import numpy as onp

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter",
           "ResizeIter", "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape+dtype+layout descriptor (reference io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=onp.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return (f"DataDesc[{self.name},{self.shape},{self.dtype},"
                f"{self.layout}]")

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One mini-batch (reference io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "Data must be a list"
        if label is not None:
            assert isinstance(label, (list, tuple)), "Label must be a list"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return (f"{self.__class__.__name__}: data shapes: {data_shapes} "
                f"label shapes: {label_shapes}")


class DataIter:
    """Base iterator (reference io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy) (reference
    io_utils.init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (onp.ndarray, nd.NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError(
            "Input must be NDArray, numpy.ndarray, a list of them or dict "
            "with them as values")
    out = []
    for k, v in data.items():
        if isinstance(v, nd.NDArray):
            v = v.asnumpy()
        out.append((k, onp.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator (reference io.py:491): shuffle, pad/discard/
    roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = onp.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.batch_size = batch_size
        self.cursor = -self.batch_size
        self.num_data = self.idx.shape[0]
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype)
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype)
            for k, v in self.label
        ]

    def hard_reset(self):
        if self.shuffle:
            self._shuffle_data()
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None

    def reset(self):
        if self.shuffle:
            self._shuffle_data()
        if (self.last_batch_handle == "roll_over"
                and self.num_data - self.batch_size < self.cursor
                < self.num_data):
            self.cursor = self.cursor - self.num_data - self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        data = self.getdata()
        label = self.getlabel()
        if data and data[0].shape[0] != self.batch_size:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle == "roll_over":
                # cache the incomplete tail; it heads next epoch's first
                # batch (reference io.py roll_over semantics)
                self._cache_data = data
                self._cache_label = label
                raise StopIteration
        batch = DataBatch(
            data=data, label=label, pad=self.getpad(), index=None)
        if (self.last_batch_handle == "roll_over"
                and self._cache_data is not None):
            self._cache_data = None
            self._cache_label = None
        return batch

    def _getdata(self, data_source, start=None, end=None):
        assert start is not None or end is not None
        if start is None:
            start = 0
        if end is None:
            end = data_source[0][1].shape[0] if data_source else 0
        return [nd.array(x[1][start:end]) for x in data_source]

    def _concat(self, first_data, second_data):
        return [
            nd.concat(first_data[i], second_data[i], dim=0)
            for i in range(len(first_data))
        ]

    def _batchify(self, data_source, cache):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if (self.last_batch_handle == "roll_over"
                and -self.batch_size < self.cursor < 0):
            # first batch of an epoch following a cached partial tail
            assert cache is not None, (
                "roll_over: first epoch should not have a negative cursor")
            second_part = self._getdata(
                data_source, 0, self.cursor + self.batch_size)
            if not cache:
                return second_part
            return self._concat(cache, second_part)
        if self.cursor + self.batch_size <= self.num_data:
            return self._getdata(
                data_source, self.cursor, self.cursor + self.batch_size)
        if self.last_batch_handle == "pad":
            # wrap around to fill the batch
            first_part = self._getdata(
                data_source, self.cursor, self.num_data)
            second_part = self._getdata(
                data_source, 0,
                self.batch_size - self.num_data + self.cursor)
            if not first_part:
                return first_part
            return self._concat(first_part, second_part)
        # discard / roll_over: return the partial tail as-is
        return self._getdata(data_source, self.cursor, self.num_data)

    def getdata(self):
        return self._batchify(self.data, self._cache_data)

    def getlabel(self):
        return self._batchify(self.label, self._cache_label)

    def getpad(self):
        if (self.last_batch_handle == "pad"
                and self.cursor + self.batch_size > self.num_data):
            return self.cursor + self.batch_size - self.num_data
        if (self.last_batch_handle == "roll_over" and -self.batch_size
                < self.cursor < 0):
            return -self.cursor
        return 0

    def _shuffle_data(self):
        onp.random.shuffle(self.idx)
        self.data = [(k, v[self.idx]) for k, v in self.data]
        self.label = [(k, v[self.idx]) for k, v in self.label]


class ResizeIter(DataIter):
    """Resize another iterator to `size` batches per epoch (reference
    io.py:282)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators (reference
    io.py:347; C++ analog src/io/iter_prefetcher.h).

    ``device_feed`` (None = follow MXNET_DEVICE_FEED, default on)
    additionally ``device_put``s each prefetched batch inside the
    prefetch thread, so the host->HBM transfer of the NEXT batch
    overlaps the running step instead of blocking it — the reference
    prefetcher only double-buffered host memory."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 device_feed=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        if device_feed is None:
            from .device_feed import device_feed_enabled

            device_feed = device_feed_enabled()
        self._device_feed = bool(device_feed)
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    batch = self.iters[i].next()
                    if self._device_feed:
                        from .device_feed import as_device_batch

                        batch = as_device_batch(batch)
                    self.next_batch[i] = batch
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i])
            for i in range(self.n_iter)
        ]
        for thread in self.prefetch_threads:
            thread.daemon = True
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()
        for thread in self.prefetch_threads:
            thread.join()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([
            [
                DataDesc(r[x.name], x.shape, x.dtype)
                if isinstance(x, DataDesc) else DataDesc(*x)
                for x in i.provide_data
            ]
            for r, i in zip(self.rename_data, self.iters)
        ], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([
            [
                DataDesc(r[x.name], x.shape, x.dtype)
                if isinstance(x, DataDesc) else DataDesc(*x)
                for x in i.provide_label
            ]
            for r, i in zip(self.rename_label, self.iters)
        ], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, (
                    "Number of entry mismatches between iterators")
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, (
                "Number of entry mismatches between iterators")
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad
