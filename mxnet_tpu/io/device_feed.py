"""Async double-buffered device feed.

Reference analog: src/io/iter_prefetcher.h double-buffers HOST batches;
the reference's GPU copy then overlaps via CUDA streams inside the
engine.  XLA has no implicit H2D overlap for python-side ``device_put``
— every step in the old path paid a blocking host->HBM transfer after
``next()`` returned.  ``DeviceFeedIter`` closes that gap: a background
thread pulls host batches from any iterator and ``device_put``s them
(mesh-sharded when the consuming step is SPMD) so up to ``depth``
batches are already resident in HBM while the current step runs.
Host assembly AND the H2D transfer overlap compute; the consumer's
``next()`` returns device-committed arrays.

Wired in by default (``MXNET_DEVICE_FEED``): ``gluon.data.DataLoader``
wraps its per-epoch iterator, ``Module.fit`` wraps ``train_data``, and
``bench.py`` feeds its measured steps through one.  Works with any
source: ``DataIter`` subclasses (DataBatch items), ``DataLoader``
iterators (lists of NDArrays), or plain generators of numpy arrays.
"""
from __future__ import annotations

import queue
import threading
import time

from .. import ndarray as nd
from ..base import MXNetError
from .io import DataBatch, DataIter

__all__ = ["DeviceFeedIter", "as_device_batch", "batch_nbytes",
           "device_feed_enabled"]

_END = object()


class _Err:
    def __init__(self, exc):
        self.exc = exc


def _q_put(q, stop, item):
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _produce(base, q, stop, stats, sharding, device, n_shards):
    """Producer loop (module-level on purpose: it must not hold a
    reference to the DeviceFeedIter, or an abandoned iterator could
    never be garbage-collected and its finalizer never fire)."""
    from ..resilience import faultsim
    from ..resilience.retry import retry_call

    try:
        src = iter(base)
        while not stop.is_set():
            try:
                item = next(src)
            except StopIteration:
                _q_put(q, stop, _END)
                return
            t0 = time.perf_counter()

            def put_batch(it=item):
                # feed.h2d: the injection point for transfer faults;
                # transient failures (injected or OS-level) retry with
                # bounded backoff instead of killing the epoch
                faultsim.inject("feed.h2d")
                return as_device_batch(it, sharding, device, n_shards)

            out = retry_call(
                put_batch,
                retry_on=(faultsim.FaultInjected, OSError),
                attempts=3, base_delay=0.02, max_delay=0.5)
            stats["producer_busy_s"] += time.perf_counter() - t0
            stats["h2d_bytes"] += batch_nbytes(out)
            if not _q_put(q, stop, out):
                return
    except BaseException as e:  # noqa: BLE001 — surfaced on next()
        _q_put(q, stop, _Err(e))


def device_feed_enabled():
    from ..config import get_env

    return bool(get_env("MXNET_DEVICE_FEED"))


def _batch_sharding(mesh, data_axis):
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(data_axis))


def _put_array(v, sharding, device, n_shards):
    import jax

    if sharding is not None and getattr(v, "ndim", 0) >= 1 \
            and v.shape[0] % n_shards == 0:
        return jax.device_put(v, sharding)
    if device is not None:
        return jax.device_put(v, device)
    return jax.device_put(v)


def as_device_batch(item, sharding=None, device=None, n_shards=1):
    """Recursively move a batch's arrays to the device: NDArrays stay
    NDArrays (committed), numpy arrays become committed NDArrays, raw
    jax arrays stay raw; DataBatch structure/pad/index are preserved."""
    import numpy as onp

    import jax

    if item is None:
        return None
    if isinstance(item, DataBatch):
        return DataBatch(
            data=as_device_batch(item.data, sharding, device, n_shards),
            label=as_device_batch(item.label, sharding, device,
                                  n_shards),
            pad=item.pad, index=item.index, bucket_key=item.bucket_key,
            provide_data=item.provide_data,
            provide_label=item.provide_label)
    if isinstance(item, (list, tuple)):
        mapped = [as_device_batch(x, sharding, device, n_shards)
                  for x in item]
        return type(item)(mapped) if isinstance(item, tuple) else mapped
    if isinstance(item, nd.NDArray):
        return nd.NDArray(_put_array(item._data, sharding, device,
                                     n_shards))
    if isinstance(item, onp.ndarray):
        return nd.NDArray(_put_array(item, sharding, device, n_shards))
    if isinstance(item, jax.Array):
        return _put_array(item, sharding, device, n_shards)
    return item


def batch_nbytes(item):
    """Total array bytes in a (device) batch — the per-batch H2D
    transfer volume ``stats()['h2d_bytes']`` accumulates and telemetry
    step records report as deltas."""
    if item is None:
        return 0
    if isinstance(item, DataBatch):
        return batch_nbytes(item.data) + batch_nbytes(item.label)
    if isinstance(item, (list, tuple)):
        return sum(batch_nbytes(x) for x in item)
    data = item._data if isinstance(item, nd.NDArray) else item
    nbytes = getattr(data, "nbytes", None)
    return int(nbytes) if nbytes is not None else 0


class DeviceFeedIter(DataIter):
    """Wrap any batch iterator; keep ``depth`` batches device-resident
    ahead of the consumer (mesh-sharded over ``data_axis`` when a mesh
    is given).

    ``reset()`` restarts the producer and resets the wrapped source, so
    the wrapper drops into ``Module.fit``'s epoch loop in place of the
    raw iterator.  ``stats()`` reports how long the consumer actually
    waited vs how long the producer spent assembling+transferring — the
    feed/compute overlap evidence bench.py puts in its JSON.
    """

    def __init__(self, base, depth=None, mesh=None, data_axis="data",
                 device=None):
        from ..config import get_env

        super().__init__(getattr(base, "batch_size", 0))
        self._base = base
        self._depth = max(1, int(depth if depth is not None
                                 else get_env("MXNET_DEVICE_FEED_DEPTH")))
        self._sharding = _batch_sharding(mesh, data_axis)
        self._n_shards = int(mesh.devices.size) if mesh is not None else 1
        self._device = device
        self._stats = {"batches": 0, "epochs": 0,
                       "consumer_wait_s": 0.0, "producer_busy_s": 0.0,
                       "h2d_bytes": 0}
        self._thread = None
        self._done = False
        self._closed = False
        self._start()

    # --------------------------------------------------------- producer
    def _start(self):
        import weakref

        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self._depth)
        # the thread closes over the queue/event/stats — NOT self — so
        # an abandoned wrapper (consumer broke out of the epoch and
        # dropped it) stays collectible; the GC finalizer then releases
        # the producer instead of leaking a thread + `depth` device
        # batches for the life of the process
        self._thread = threading.Thread(
            target=_produce,
            args=(self._base, self._q, self._stop, self._stats,
                  self._sharding, self._device, self._n_shards),
            name="DeviceFeedIter", daemon=True)
        self._finalizer = weakref.finalize(self, self._stop.set)
        self._thread.start()

    def _halt(self, timeout=None):
        """Stop the producer with a BOUNDED join: a wedged producer
        (stuck inside a native H2D call) is abandoned as a daemon
        after the timeout instead of hanging fit teardown — the stop
        event keeps it from ever touching the queue again.  Returns
        True when the thread actually exited."""
        if self._thread is None:
            return True
        self._stop.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if timeout is None:
            from ..config import get_env

            timeout = float(get_env("MXNET_FEED_JOIN_TIMEOUT_SEC"))
        t = self._thread
        t.join(timeout=timeout)
        joined = not t.is_alive()
        if not joined:
            import logging

            logging.warning(
                "DeviceFeedIter: producer did not join within %.1fs; "
                "abandoning daemon thread", timeout)
        self._thread = None
        return joined

    # --------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __len__(self):
        # generator bases have no length; raise the TypeError len()
        # itself would, so try/except-len consumers (tqdm et al.) fall
        # back exactly as they would on the unwrapped iterator
        if getattr(type(self._base), "__len__", None) is None:
            raise TypeError(
                "DeviceFeedIter: wrapped source has no length")
        return len(self._base)

    def next(self):
        if self._done:  # exhausted: don't block on a dead producer
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    raise MXNetError(
                        "DeviceFeedIter: producer thread died without "
                        "a sentinel")
        self._stats["consumer_wait_s"] += time.perf_counter() - t0
        if item is _END:
            self._done = True
            raise StopIteration
        if isinstance(item, _Err):
            self._done = True
            raise item.exc
        self._stats["batches"] += 1
        return item

    def reset(self):
        self._halt()
        if hasattr(self._base, "reset"):
            self._base.reset()
        self._stats["epochs"] += 1
        self._done = False
        self._closed = False
        self._start()

    def close(self):
        """Stop the producer WITHOUT touching the wrapped source.  An
        owner that wrapped someone else's iterator (Module.fit) must
        close before handing the source back — a live producer keeps
        consuming from it and would race the next consumer.

        Idempotent, and the producer join is bounded
        (MXNET_FEED_JOIN_TIMEOUT_SEC) so a preemption drain can never
        hang in teardown; after close(), next() raises StopIteration
        until reset() revives the wrapper."""
        if self._closed:
            return
        self._closed = True
        self._done = True
        self._halt()

    @property
    def base(self):
        return self._base

    def stats(self):
        return dict(self._stats)

    # ------------------------------------------------- passthrough meta
    @property
    def provide_data(self):
        return getattr(self._base, "provide_data", None)

    @property
    def provide_label(self):
        return getattr(self._base, "provide_label", None)
