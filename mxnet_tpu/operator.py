"""Custom operator API — user-defined ops in Python.

Reference parity: python/mxnet/operator.py (CustomOp :488, CustomOpProp
:712, register :1114 → src/operator/custom/custom-inl.h, which executes
the Python callbacks outside the engine threads).

TPU-native design: custom ops run EAGERLY on the host (they are
arbitrary Python, by definition outside the compiled program — the
reference makes the same tradeoff, custom-inl.h:178 async-executes them
off the engine).  Autograd integration goes through the same tape as
built-in ops: the user's ``backward`` becomes the node's pull-back.
Inside jit-traced code (hybridize), custom ops raise — matching the
reference's inability to fuse them into CachedOp segments.
"""
from __future__ import annotations

import numpy as onp

from . import autograd
from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered",
           "custom"]

_REGISTRY: dict[str, type] = {}


class CustomOp:
    """Base class for custom op implementations (reference
    operator.py:488)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad request."""
        if req == "null":
            return
        if req == "add":
            dst._adopt(dst._data + src._data)
        else:  # write / inplace
            dst._adopt(src._data.astype(dst._data.dtype))


class CustomOpProp:
    """Op metadata + factory (reference operator.py:712)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        t = in_type[0]
        return ([t] * len(self.list_arguments()),
                [t] * len(self.list_outputs()),
                [t] * len(self.list_auxiliary_states()))

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type`` (reference
    operator.py:1114)."""

    def _do(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("can only register CustomOpProp subclasses")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _do


def get_all_registered():
    return dict(_REGISTRY)


def custom(*inputs, op_type, **params):
    """Invoke a registered custom op (the ``mx.nd.Custom`` entry point).

    Runs the user's ``forward`` eagerly; when autograd is recording, a
    tape node wraps the user's ``backward``.
    """
    import jax.numpy as jnp

    from . import ndarray as nd
    from .ndarray.ndarray import NDArray

    if op_type not in _REGISTRY:
        raise MXNetError(f"custom op {op_type!r} is not registered")
    import jax

    for i in inputs:
        if isinstance(i, NDArray) and isinstance(i._data,
                                                 jax.core.Tracer):
            raise MXNetError(
                "custom ops run eagerly on the host and cannot be "
                "traced into a compiled program (reference parity: "
                "CustomOp executes outside the engine)")
    prop = _REGISTRY[op_type](**{k: str(v) for k, v in params.items()})
    in_nd = [i if isinstance(i, NDArray) else nd.array(i)
             for i in inputs]
    in_shapes = [list(i.shape) for i in in_nd]
    _, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    _, out_types, aux_types = prop.infer_type([i.dtype for i in in_nd])
    op = prop.create_operator(None, in_shapes,
                              [i.dtype for i in in_nd])
    out_nd = [nd.zeros(tuple(s), dtype=t)
              for s, t in zip(out_shapes, out_types)]
    aux = [nd.zeros(tuple(s), dtype=t)
           for s, t in zip(aux_shapes, aux_types)]
    op.forward(is_train=autograd.is_training(),
               req=["write"] * len(out_nd), in_data=in_nd,
               out_data=out_nd, aux=aux)

    if autograd.is_recording() and any(
            i._is_var or i._node is not None for i in in_nd):
        def vjp_fn(out_grads):
            if not isinstance(out_grads, tuple):
                out_grads = (out_grads,)
            in_grad = [nd.zeros(i.shape, dtype=i.dtype) for i in in_nd]
            og = [NDArray(jnp.asarray(g)) for g in out_grads]
            op.backward(req=["write"] * len(in_nd), out_grad=og,
                        in_data=in_nd, out_data=out_nd,
                        in_grad=in_grad, aux=aux)
            return tuple(g._data for g in in_grad)

        node = autograd.TapeNode(
            vjp_fn, list(in_nd),
            [(o.shape, o.dtype) for o in out_nd],
            op_name=f"Custom[{op_type}]")
        for idx, o in enumerate(out_nd):
            o._node = node
            o._oidx = idx
    return out_nd[0] if len(out_nd) == 1 else out_nd
