"""Environment-variable registry + declarative parameter structs.

Reference parity: SURVEY.md §5.6 — the ~100 ``MXNET_*``/``DMLC_*``
knobs read via dmlc::GetEnv (docs env_var.md) and the
``dmlc::Parameter`` declarative structs every op/iterator uses for
kwarg parsing, defaults, range checks and doc generation.

TPU-native: XLA owns scheduling/memory, so engine-thread and
memory-pool knobs are accepted for compatibility but documented as
no-ops; the live knobs configure the host-side data plane, profiler
autostart and distributed bootstrap.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

from .base import MXNetError

__all__ = ["register_env", "get_env", "list_env", "describe_env",
           "setup_compilation_cache", "ParamStruct", "field"]

_ENV: dict[str, "EnvVar"] = {}


@dataclasses.dataclass
class EnvVar:
    name: str
    default: Any
    type: Callable
    doc: str
    live: bool = True  # False = accepted for reference compat, no-op


def register_env(name, default, typ=str, doc="", live=True):
    _ENV[name] = EnvVar(name, default, typ, doc, live)
    return _ENV[name]


def get_env(name):
    """Typed read of a registered env var (dmlc::GetEnv analog)."""
    if name not in _ENV:
        raise MXNetError(f"env var {name} is not registered")
    ev = _ENV[name]
    raw = os.environ.get(name)
    if raw is None:
        return ev.default
    try:
        if ev.type is bool:
            return raw.lower() in ("1", "true", "yes", "on")
        return ev.type(raw)
    except (TypeError, ValueError) as e:
        raise MXNetError(f"invalid value {raw!r} for {name}") from e


def list_env():
    return sorted(_ENV)


def describe_env():
    """The env_var.md-style table, generated from the registry."""
    lines = ["| Variable | Default | Live | Description |",
             "|---|---|---|---|"]
    for name in list_env():
        ev = _ENV[name]
        lines.append(f"| {name} | {ev.default!r} | "
                     f"{'yes' if ev.live else 'compat no-op'} | "
                     f"{ev.doc} |")
    return "\n".join(lines)


# ----------------------------------------------------- the framework knobs
register_env("MXNET_CPU_WORKER_NTHREADS", 0, int,
             "Host decode/augment worker threads (0 = all cores); feeds "
             "ImageRecordIter preprocess_threads default.")
register_env("MXNET_TPU_PREFETCH_BUFFER", 4, int,
             "Batches kept ready ahead of the training loop "
             "(ImageRecordIter prefetch_buffer default).")
register_env("MXNET_IO_WORKERS", 0, int,
             "Decode/augment worker pool size behind ImageRecordIter/"
             "ImageDetRecordIter (round 17).  0 (default) preserves "
             "the single-producer-thread behavior; N>0 runs N workers "
             "behind a sequence-ordered emitter — batch assembly is "
             "by index plan, so worker count, respawns and stragglers "
             "never perturb which sample lands in which batch row.")
register_env("MXNET_IO_WORKER_RESPAWN", 2, int,
             "Respawn budget of the io worker pool: a worker that "
             "dies holding a batch or wedges past the per-batch "
             "deadline is replaced (its batch re-dispatched) at most "
             "this many times per iterator; exhausting the budget "
             "fails LOUDLY with the quarantine manifest attached.")
register_env("MXNET_IO_MAX_SKIP_FRAC", 0.1, float,
             "Quarantine ceiling: the fraction of a .rec shard's "
             "records that may be skipped (framing resyncs + "
             "unpack/decode quarantines) before the data plane "
             "refuses to continue — corrupt records degrade "
             "structurally (skip + counter + manifest) up to this "
             "bound, but the pipeline never silently trains on a "
             "substantially shrunken dataset.")
register_env("MXNET_PROFILER_AUTOSTART", False, bool,
             "Start the profiler at import (reference knob; wired to "
             "mx.profiler.set_state('run')).")
register_env("MXNET_PROFILER_MODE", "imperative", str,
             "Default profiler scope (symbolic/imperative/all).")
register_env("MXNET_ENFORCE_DETERMINISM", False, bool,
             "Force full fp32 matmul precision on the MXU (slower, "
             "reproducible to the ulp).")
register_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000, int,
             "Flat-bucket split threshold (elements) for the sharded-"
             "server gradient exchange (optimizer_sharding='ps', "
             "parallel.zero): a bucket closes once the next parameter "
             "would push it past this many elements — the authentic "
             "ps-lite bound above which arrays are sliced across "
             "servers.  Fewer, larger buckets mean fewer collective "
             "launches; the collectives-budget CI gate runs at 4e6.")
register_env("MXNET_OPTIMIZER_SHARDING", "", str,
             "Sharded-server optimizer (ZeRO-1 as the TPU-native "
             "parameter server): 'ps'/'1' forces it on for every "
             "make_train_step/Module mesh, '0'/'off' forces it off "
             "(overriding the kvstore='dist_sync' mapping and explicit "
             "opt-ins), empty defers to the caller.  Gradients "
             "reduce-scatter in flat buckets, the optimizer updates "
             "only the locally-owned shard (state lives sharded), and "
             "the params all-gather back.")
register_env("MXNET_ZERO_STAGE", "", str,
             "ZeRO stage of the sharded-server exchange "
             "(optimizer_sharding='ps', parallel.zero): '1' = classic "
             "ZeRO-1 (per-bucket all-reduce, grads replicated, "
             "optimizer state sharded), '2' = gradient shards "
             "(per-bucket reduce-scatter — the default program when "
             "unset), '3' = parameter shards (params live sharded as "
             "flat buckets; the forward all-gathers each bucket with "
             "bucket-wise prefetch and nothing gathers back).  Setting "
             "a stage also opts the step into sharding under a mesh; "
             "unset defers to the caller's zero_stage/optimizer_"
             "sharding arguments.  Unknown values raise.")
register_env("MXNET_COLLECTIVES_BUDGET", 8, int,
             "Per-step collective-launch budget the dp dryrun verdict "
             "gates against under optimizer_sharding='ps': at most "
             "this many reduce-scatters and all-gathers (and <=2 "
             "stray all-reduces) in the compiled step's HLO.")
register_env("MXNET_ENGINE_TYPE", "XLA", str,
             "Reference engine selector; the XLA async runtime is the "
             "only engine.", live=False)
register_env("MXNET_EXEC_BULK_EXEC_TRAIN", True, bool,
             "Reference bulking knob; XLA fusion subsumes op bulking.",
             live=False)
register_env("MXNET_GPU_MEM_POOL_TYPE", "Naive", str,
             "Reference allocator strategy; XLA owns HBM pooling.",
             live=False)
register_env("JAX_COMPILATION_CACHE_DIR", "", str,
             "Persistent XLA compilation cache directory.  When set, "
             "every jitted program (train step, CachedOp, executor, "
             "predictor) is cached on disk keyed by HLO, so re-binds "
             "and bench recaptures skip recompilation entirely.  The "
             "reference analog is the cuDNN algo registry persisting "
             "autotune winners across Bind calls.")
register_env("MXNET_CONV_1X1_DOT", False, bool,
             "Lower channel-last 1x1 convolutions to dot_general "
             "(native MXU matmul, no layout change).  Off by default; "
             "bench.py's --conv-ab switch measures the step-level A/B. "
             "When set explicitly it overrides any autotuned winner.")
register_env("MXNET_AUTOTUNE", 1, int,
             "In-step variant autotuner (mxnet_tpu.autotune; the "
             "cudnn_tune/cudnn_algoreg analog): 0 = off, 1 = consult "
             "the persisted winner cache and tune where sample data "
             "is provided, 2 = re-tune even on a cache hit "
             "(cudnn_tune='fastest' on every bind).")
register_env("MXNET_AUTOTUNE_CACHE_DIR", "", str,
             "Directory for autotune.json (persisted variant winners). "
             "Empty = next to JAX_COMPILATION_CACHE_DIR, falling back "
             "to ~/.cache/mxnet_tpu.")
register_env("MXNET_PALLAS_OPT", "", str,
             "Hand override for the 'fused_bucket_opt' autotune "
             "variant (round 14): 1 forces the Pallas fused-bucket "
             "optimizer kernels (ops/pallas_opt.py — prep + update + "
             "loss-scale check in one VMEM pass), 0 forces the jnp "
             "fused_bucket_update.  Unset: the in-step race decides "
             "per (shape, dtype, platform, mesh).")
register_env("MXNET_FLASH_ATTENTION", "", str,
             "Hand override for the 'flash_attention' autotune "
             "variant (round 14): naive/0, pallas/1, pallas_b256 "
             "(256x256 blocks), or pallas_pad (tile-align by padding "
             "+ masked keys).  Unset: cached winner, then the "
             "TPU+tiling heuristic.")
register_env("MXNET_DTYPE_LADDER", "", str,
             "The dtype-ladder knob (round 14; fp8 rung round 19). "
             "Unset/0: the ladder never races or applies (a dtype "
             "change is not numerics-neutral, so it is opt-in).  "
             "1/auto: make_train_step races fp32 vs bf16 compute "
             "in-step (compute_dtype=None steps only) and applies the "
             "cached per-program winner.  A comma roster like "
             "'fp32,bf16,fp8' races exactly those rungs — fp8 (e4m3 "
             "fwd / e5m2 grad, delayed per-tensor scaling in "
             "opt_state) only ever joins by being named.  "
             "bf16/fp32/fp8: hand-pin the arm.")
register_env("MXNET_FP8_AMAX_HISTORY", 16, int,
             "Length of the rolling amax history behind the fp8 "
             "rung's delayed scaling (round 19): each quantized "
             "tensor class (input / weights / grads) carries this "
             "many steps of observed |t|_inf in opt_state['_fp8'], "
             "and the next step's scale is fp8_max / (2 * max "
             "(history)) — in-graph, no host sync "
             "(ops/pallas_opt.fp8_delayed_scale).")
register_env("MXNET_BNRELUCONV_VARIANT", "", str,
             "Hand override for the 'pallas_bnreluconv' autotune "
             "variant: stock (unfused layer path), jnp (fused op, jnp "
             "backward), pallas (fused op, one-pass Pallas backward). "
             "Unset: cached per-shape winner, then "
             "MXNET_FUSED_BNRELUCONV.")
register_env("MXNET_DEVICE_FEED", True, bool,
             "Async double-buffered device feed: DataLoader / "
             "Module.fit / bench.py wrap their batch source in "
             "io.DeviceFeedIter so host batch assembly and the "
             "host->HBM transfer overlap the running step.  0 restores "
             "the blocking per-step device_put.")
register_env("MXNET_DEVICE_FEED_DEPTH", 2, int,
             "Batches DeviceFeedIter keeps already device_put (and "
             "mesh-sharded) ahead of the consumer.")
register_env("MXNET_EXEC_DONATE", True, bool,
             "Donate dead executor state buffers (updated BatchNorm "
             "moving stats in the CachedOp/Executor jit paths) back to "
             "XLA for in-place reuse — the TPU-native analog of the "
             "reference's static_alloc memory sharing.")
register_env("MXNET_PS_DEADLINE_SEC", 600.0, float,
             "Parameter-server wait deadline (seconds) for sync "
             "round-skew waits and pull/spull readiness waits — was "
             "four hard-coded 600 s constants in _ps.py.  Lower it so "
             "fault-injection tests fail in seconds; raise it for "
             "slow-merge real deployments.")
register_env("MXNET_FAULT_SPEC", "", str,
             "Deterministic fault injection spec for "
             "resilience.faultsim, e.g. "
             "'ckpt.write:crash@3;ps.push:delay=2.0@7' — "
             "point:action[=value]@hits clauses armed by per-point "
             "hit count.  Empty = disarmed (counters only).")
register_env("MXNET_BAD_STEP_LIMIT", 0, int,
             "Step-level NaN/Inf guard: >0 arms it — a non-finite "
             "step is skipped (params/optimizer state held, like "
             "dynamic loss scaling) and after this many CONSECUTIVE "
             "bad steps Module.fit restores the last good checkpoint "
             "and raises a diagnostic error.  0 disables the guard "
             "(no per-step device sync on the fast path).")
register_env("MXNET_CKPT_KEEP", 3, int,
             "Checkpoint versions Module.fit's internal manager "
             "retains (resilience.checkpoint keep_n); older "
             "params/states/manifest files are pruned after each "
             "save.  Explicit CheckpointManager users choose their "
             "own keep_n (None = keep all).")
register_env("MXNET_FEED_JOIN_TIMEOUT_SEC", 10.0, float,
             "Bound on DeviceFeedIter.close()'s producer-thread join: "
             "a wedged producer is abandoned (daemon) after this many "
             "seconds so a preemption drain can never hang fit "
             "teardown.")
register_env("MXNET_RUNLOG", "", str,
             "Path of the per-step JSONL run log (telemetry.RunLog). "
             "Empty = telemetry off entirely: every wire point takes "
             "the no-op fast exit and the fit loop performs no "
             "per-step device syncs.  Set it and every subsystem "
             "(step timing, device feed, compile/retrace causes, "
             "checkpoints, PS retries, NaN guard, fault injections) "
             "reports into one line-buffered JSONL file, plus a crash "
             "flight recorder at <path>.flight.json.")
register_env("MXNET_TELEMETRY_SAMPLE", 25, int,
             "Device-sync sampling period for telemetry: the fit loop "
             "reads the loss/metric (one device sync) only every this "
             "many steps; unsampled step records keep wall timing but "
             "loss=null so the hot path stays async.")
register_env("MXNET_FLIGHTREC_DEPTH", 64, int,
             "Crash flight recorder ring depth: the last N step "
             "records (plus config/env/compile fingerprints) dumped "
             "atomically on SIGTERM drain, NaN-abort, fault-injection "
             "crash or an unhandled exception inside Module.fit.  "
             "0 disables the recorder (run log still written).")
register_env("MXNET_WATCHDOG_SEC", 0.0, float,
             "Hang watchdog (telemetry.Watchdog): >0 arms a background "
             "thread per bench phase / per Module.fit that, when the "
             "heartbeat goes quiet for this many seconds — even with "
             "the main thread blocked inside an uninterruptible XLA "
             "call — appends an all-thread faulthandler stack dump, "
             "flushes the crash flight recorder with reason 'stall', "
             "and emits a 'watchdog' run-log record.  It observes, it "
             "never kills.  0 (default) = no thread, zero hot-path "
             "cost.")
register_env("MXNET_NUMERICS", False, bool,
             "In-graph numerics monitor (telemetry.numerics, Monitor "
             "2.0): compile per-gradient summary reductions "
             "(l2/min/max/NaN/Inf counts/zero fraction) into the "
             "train step and record sampled 'tensor_stats' run-log "
             "records — so a NaN step is EXPLAINED (which tensor, "
             "which step) before the bad-step guard aborts.  Off by "
             "default: the traced program is bit-identical to a build "
             "without the monitor.")
register_env("MXNET_NUMERICS_SAMPLE", 0, int,
             "Steps between numerics-monitor tensor_stats emissions "
             "(each costs one device readback of the summary "
             "vectors).  0 = follow MXNET_TELEMETRY_SAMPLE.")
register_env("MXNET_METRICS_TEXTFILE", "", str,
             "Prometheus-textfile export path (node_exporter textfile "
             "collector convention): telemetry counters + last "
             "throughput/loss, atomically rewritten on every sampled "
             "step.  Empty = off.")
register_env("MXNET_TRACE_CONTEXT", "", str,
             "Inbound W3C traceparent stamp "
             "('00-<32hex trace>-<16hex span>-01') set by a spawner "
             "(fleet replica launch, online-loop trainer, healing "
             "relaunch) so the child's spans parent onto the spawn "
             "(telemetry.tracing).  Empty = this process roots its "
             "own traces.", live=False)
register_env("MXNET_PROCESS_ROLE", "", str,
             "Process identity stamped by spawners into the child's "
             "run_start record (trainer|replica|router|io_worker|"
             "bench|fit) — the track-group label tools/tracemerge.py "
             "uses for the merged timeline.", live=False)
register_env("MXNET_PROCESS_RANK", "", str,
             "Numeric rank within the role (replica index, trainer "
             "attempt), stamped next to MXNET_PROCESS_ROLE into "
             "run_start.", live=False)
register_env("MXNET_ELASTIC", False, bool,
             "Elastic multi-host runtime (resilience.elastic): arms "
             "runtime.init_distributed()/elastic_init() multi-process "
             "bring-up over jax.distributed, dp x tp meshes spanning "
             "hosts, topology-stamped checkpoints, and reshard-on-"
             "resize resume — a job resumed at a different world size "
             "re-plans buckets and re-shards optimizer state instead "
             "of dying.")
register_env("MXNET_COORDINATOR", "", str,
             "jax.distributed coordinator address as host:port "
             "(process 0 binds it).  Empty falls back to the DMLC_* "
             "launcher contract (DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT "
             "when DMLC_NUM_WORKER > 1); unresolvable = single-process "
             "bring-up.")
register_env("MXNET_NUM_PROCESSES", 0, int,
             "Process count of the elastic job (0 = fall back to "
             "DMLC_NUM_WORKER, then single-process).")
register_env("MXNET_PROCESS_ID", -1, int,
             "This process's id in the elastic job (-1 = fall back to "
             "DMLC_WORKER_ID).")
register_env("MXNET_DIST_INIT_ATTEMPTS", 4, int,
             "Bounded-retry attempts around jax.distributed.initialize "
             "in elastic_init (backoff + jitter via resilience.retry; "
             "the dist.init fault point fires inside every attempt).")
register_env("MXNET_DIST_INIT_TIMEOUT_SEC", 120.0, float,
             "Total time budget (seconds) for elastic_init's "
             "initialize retry loop — the deadline_sec cap, so attempt "
             "counts cannot overshoot the bring-up SLA once backoff "
             "grows.")
register_env("MXNET_PEER_TIMEOUT_SEC", 10.0, float,
             "Peer liveness timeout (resilience.healing): a peer "
             "whose heartbeat file goes stale for this many seconds "
             "is declared DEAD by every survivor's FailureDetector "
             "(a same-host peer whose pid vanished is declared dead "
             "immediately — the SIGKILL fast path).  Also sets the "
             "Heartbeater's default beat interval (timeout/4).")
register_env("MXNET_HEARTBEAT_DIR", "", str,
             "Shared directory of per-rank heartbeat files "
             "(resilience.healing).  Set on a multi-process elastic "
             "job and Module.fit arms the self-healing loop: this "
             "rank beats, the failure detector polls at step "
             "boundaries, and a declared peer death fires the "
             "emergency checkpoint + PeerDeadError instead of "
             "wedging in a collective.  Empty = healing unarmed.")
register_env("MXNET_CKPT_ASYNC", True, bool,
             "Snapshot checkpoints write asynchronously "
             "(CheckpointManager.save_async: device->host capture at "
             "the step boundary, serialization + atomic write on a "
             "background thread with a bounded back-pressure queue). "
             "0 forces the MXNET_SNAPSHOT_EVERY cadence writes "
             "synchronous — the A/B arm and a debugging escape "
             "hatch.")
register_env("MXNET_SNAPSHOT_EVERY", 0, int,
             "Batches between async snapshot checkpoints in "
             "Module.fit (needs checkpoint=).  0 (default) keeps the "
             "epoch-boundary-only cadence; N>0 makes the recovery "
             "point at most N batches old — the freshest snapshot is "
             "also what an emergency checkpoint (peer death, "
             "watchdog abort) flushes without any collective.")
register_env("MXNET_HEAL_MAX_RELAUNCH", 2, int,
             "Respawn bound of the self-healing supervisor "
             "(python -m mxnet_tpu.resilience.healing --relaunch): a "
             "training command dying with a healable status (peer "
             "death rc 83, any signal kill, the faultsim crash 87) "
             "is relaunched at most this many times with "
             "MXNET_HEAL_ATTEMPT exported; anything else is final.")
register_env("MXNET_WATCHDOG_ABORT", False, bool,
             "Hang-watchdog escalation (round 16, default OFF — the "
             "observe-only contract is unchanged): after max_dumps "
             "stall dumps with the heartbeat still dead a full "
             "timeout later, flush the flight ring + the emergency "
             "checkpoint (freshest snapshot) and os._exit(85), so a "
             "permanently wedged job is rescheduled instead of "
             "burning its whole wall budget.")
register_env("MXNET_SERVE_SLO_MS", 100.0, float,
             "Default per-request deadline (milliseconds) of the "
             "serving runtime (mxnet_tpu.serving.ModelServer): a "
             "submit() without an explicit deadline_ms gets this SLO. "
             "Admission control sheds requests the latency EWMA says "
             "cannot finish inside it.")
register_env("MXNET_SERVE_QUEUE_DEPTH", 256, int,
             "Serving request-queue bound: submits beyond this many "
             "waiting requests are rejected with a structured "
             "ServeRejected(reason='queue_full') instead of growing "
             "an unbounded backlog.")
register_env("MXNET_SERVE_MAX_INFLIGHT", 0, int,
             "Bound on admitted-but-unfinished serving requests "
             "(queued + in the running batch).  0 = queue depth plus "
             "one max-size batch.")
register_env("MXNET_SERVE_BREAKER_LIMIT", 3, int,
             "Serving circuit breaker: after this many CONSECUTIVE "
             "model-invocation failures (exceptions or non-finite "
             "outputs — the bad-step machinery's serving analog) the "
             "breaker opens: requests get fast structured rejections "
             "while the batcher re-warms on probe batches; a probe "
             "success closes it.")
register_env("MXNET_FLEET_REPLICAS", 2, int,
             "Default replica-process count of a spawned serving "
             "fleet (serving.FleetRouter.spawn); the queue-depth "
             "autoscaler grows/shrinks from here within its "
             "min/max bounds.")
register_env("MXNET_FLEET_PORT", 0, int,
             "Default bind port of the serving HTTP frontend "
             "(serving.ServeFrontend); 0 = ephemeral (replica "
             "workers publish the chosen port through their "
             "--port-file).")
register_env("MXNET_FLEET_HBM_BUDGET_MB", 0.0, float,
             "Per-host model-residency budget in MiB for "
             "serving.ModelHost: a .mxje artifact is admitted only "
             "if its describe_program() memory_analysis reserved "
             "bytes fit next to the resident models, else a "
             "structured ServeRejected(reason='hbm_budget').  "
             "0 = unlimited.")
register_env("MXNET_QUANTIZE", "", str,
             "Hand override of the quantized-inference adoption "
             "race (mxnet_tpu.quantization; autotune variant ops "
             "quantized_conv/quantized_fc): 0/off/fp32 pins every "
             "rewritten layer to its fp32 fallback arm, 1/on/int8 "
             "pins the int8 program, fp8 pins the fp8 program "
             "(e4m3 operands, f32 accumulation — round 19).  "
             "Unset/auto: the in-step race's persisted winner "
             "decides per (op, shape, platform).")
register_env("MXNET_QUANT_CALIB_MODE", "naive", str,
             "Default calibration mode of quantization.calibrate: "
             "'naive' (running min/max per observed tensor) or "
             "'entropy' (KL-divergence-optimal symmetric threshold "
             "over an absolute-value histogram — the reference's "
             "calib_mode='entropy' contract, robust to rare "
             "outliers).")
register_env("MXNET_QUANT_CALIB_BATCHES", 10, int,
             "Default number of calibration batches "
             "quantization.calibrate folds through the range "
             "collector when the caller does not pass num_batches.")
register_env("MXNET_KV_PAGE_TOKENS", 16, int,
             "Tokens per KV-cache page of the generative decode "
             "server (serving.kvcache.PagedKVPool): sequences hold "
             "ceil(tokens/page_tokens) pages, so smaller pages waste "
             "less tail HBM per sequence but grow the page table the "
             "decode step walks.")
register_env("MXNET_KV_POOL_BUDGET", 4194304, int,
             "HBM byte budget of the paged KV-cache pool "
             "(serving.kvcache.PagedKVPool), the generative analog of "
             "MXNET_FLEET_HBM_BUDGET_MB: the pool sizes its physical "
             "page count to fit under this many bytes and admission "
             "is by TOKEN budget (prompt + max_new reserved up "
             "front), not request count.")
register_env("MXNET_DECODE_SLOTS", 8, int,
             "Decode-slot capacity of the generative server "
             "(serving.generate.GenerativeServer): the token-level "
             "continuous-batching step is compiled ONCE over this "
             "fixed slot tensor; sequences are admitted/evicted by "
             "in-place slot updates, never by retrace.")
register_env("MXNET_KV_DTYPE", "float32", str,
             "KV-cache storage dtype of the generative server: "
             "'float32' or 'int8' (per-(token, head) symmetric "
             "scales riding the quantization/ machinery).  int8 is "
             "adopted only if the warmup agreement probe clears the "
             "output-agreement floor, else the pool falls back to "
             "fp32 and stats['kv_dtype_effective'] says so.")
register_env("MXNET_PAGED_ATTENTION", "", str,
             "Hand override for the 'paged_decode_attention' autotune "
             "variant (round 17): gather/0 (materialize the page "
             "table's K/V then one fused softmax) or paged/1 (page-"
             "blockwise online-softmax walk).  Unset: the cached "
             "winner from the generative server's warmup race.")
register_env("MXNET_FLEET_SCALE_EWMA", 0.2, float,
             "EWMA smoothing factor of the fleet autoscaler's "
             "queue-depth signal (serving.FleetRouter): each health-"
             "probe sweep folds the per-ready-replica queue depth in "
             "with this weight; crossing scale_up_depth/"
             "scale_down_depth triggers the reshard-not-restart "
             "resize.")
register_env("MXNET_ONLINE_EXPORT_STEPS", 10, int,
             "Export cadence of the online learning loop "
             "(online.OnlineLoop): every N trainer steps the loop "
             "checkpoints, exports a v2 .mxje artifact stamped with "
             "the monotonic model version + stream cursor, and "
             "rolling-swaps it into the serving fleet.")
register_env("MXNET_FRESHNESS_SLO_MS", 60000.0, float,
             "Freshness SLO of the online loop: maximum allowed "
             "stream-sample-to-served-model latency.  Each committed "
             "swap measures newest-ingested-sample-time -> fleet-"
             "commit-time; p99 over the fault-free windows must stay "
             "under this bound (gated in benchdiff, violations "
             "counted loudly in telemetry).")
register_env("DMLC_NUM_WORKER", 1, int,
             "Distributed worker count (tools/launch.py contract).")
register_env("DMLC_WORKER_ID", 0, int, "This worker's rank.")
register_env("DMLC_PS_ROOT_URI", "127.0.0.1", str,
             "Coordinator address (worker 0).")
register_env("DMLC_PS_ROOT_PORT", "9091", str, "Coordinator port.")


# ------------------------------------------- persistent compilation cache
_CC_STATE = {"dir": None}


def setup_compilation_cache(path=None):
    """Enable jax's persistent compilation cache (no-op when unset).

    Reads ``JAX_COMPILATION_CACHE_DIR`` from the registry unless an
    explicit ``path`` is given; returns the active cache dir or None.
    Wired into bench.py, ``Module.bind``, ``make_train_step`` and the
    parallel predictor so a recapture/re-bind of an already-seen
    program costs a disk read instead of an XLA compile (the cuDNN
    algo-registry persistence analog,
    src/operator/nn/cudnn/cudnn_algoreg-inl.h).

    The min-compile-time/min-entry-size thresholds are dropped to zero
    so even small programs (the smoke-bench net, the K1 loop) hit the
    cache — bench recapture robustness matters more here than cache
    hygiene.
    """
    p = path if path is not None else get_env("JAX_COMPILATION_CACHE_DIR")
    if not p:
        return None
    if _CC_STATE["dir"] == p:
        return p  # already active — config.update churn is not free
    import jax

    os.makedirs(p, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", p)
    for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                     ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(opt, val)
        except (AttributeError, KeyError):
            pass  # knob absent in this jax — the cache still works
    _CC_STATE["dir"] = p
    return p


# ------------------------------------------------------------ ParamStruct
_MISSING = object()


def field(default=_MISSING, *, doc="", low=None, high=None, choices=None):
    """Declare one parameter (DMLC_DECLARE_FIELD analog)."""
    return {"default": default, "doc": doc, "low": low, "high": high,
            "choices": choices}


class ParamStruct:
    """Declarative parameter struct (dmlc::Parameter analog).

    Subclasses declare fields as class attributes via ``field()``;
    ``__init__(**kwargs)`` parses with defaults/range/choice checks and
    ``describe()`` generates the doc table — the same triple duty the
    reference structs serve (parse, validate, document).
    """

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        cls._fields = {}
        for base in reversed(cls.__mro__):
            for k, v in vars(base).items():
                if isinstance(v, dict) and "default" in v and "doc" in v:
                    cls._fields[k] = v

    def __init__(self, **kwargs):
        for name, spec in self._fields.items():
            if name in kwargs:
                val = kwargs.pop(name)
            elif spec["default"] is not _MISSING:
                val = spec["default"]
            else:
                raise MXNetError(
                    f"{type(self).__name__}: required parameter "
                    f"{name!r} missing")
            if spec["low"] is not None and val < spec["low"]:
                raise MXNetError(
                    f"{type(self).__name__}.{name}={val} below minimum "
                    f"{spec['low']}")
            if spec["high"] is not None and val > spec["high"]:
                raise MXNetError(
                    f"{type(self).__name__}.{name}={val} above maximum "
                    f"{spec['high']}")
            if spec["choices"] is not None and val not in spec["choices"]:
                raise MXNetError(
                    f"{type(self).__name__}.{name}={val!r} not in "
                    f"{spec['choices']}")
            setattr(self, name, val)
        if kwargs:
            raise MXNetError(
                f"{type(self).__name__}: unknown parameters "
                f"{sorted(kwargs)}")

    @classmethod
    def describe(cls):
        lines = [f"Parameters of {cls.__name__}:"]
        for name, spec in cls._fields.items():
            d = "" if spec["default"] is _MISSING else \
                f" (default {spec['default']!r})"
            lines.append(f"  {name}{d}: {spec['doc']}")
        return "\n".join(lines)

    def as_dict(self):
        return {k: getattr(self, k) for k in self._fields}
