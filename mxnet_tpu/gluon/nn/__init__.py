"""Neural network layers (reference: python/mxnet/gluon/nn/)."""
from .activations import *  # noqa: F401,F403
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from .layout import (  # noqa: F401
    channel_axis, default_layout, is_channel_last, resolve_layout)
