"""Basic layers (reference: python/mxnet/gluon/nn/basic_layers.py).

TPU-native notes: every layer lowers to registry ops that are jnp/lax
one-liners, so a hybridized net is a single fused XLA program; BatchNorm
running stats are Parameters with grad_req='null' updated functionally.
"""
from __future__ import annotations

import numpy as onp

from ... import ndarray as nd
from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError

__all__ = [
    "Sequential",
    "HybridSequential",
    "Dense",
    "Dropout",
    "BatchNorm",
    "InstanceNorm",
    "LayerNorm",
    "GroupNorm",
    "Embedding",
    "Flatten",
    "Lambda",
    "HybridLambda",
]


class Sequential(Block):
    """Stack of Blocks run in order."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(
            isinstance(c, HybridBlock) for c in self._children.values()
        ):
            import warnings

            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance."
            )
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks; hybridize() compiles the whole stack into one
    XLA program."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: data @ W.T + b (reference Dense; op parity
    src/operator/nn/fully_connected.cc)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True,
            )
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True,
                )
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _infer_param_shapes(self, x, *args):
        if self._flatten:
            in_units = int(onp.prod(x.shape[1:]))
        else:
            in_units = x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(
            x, weight, bias, no_bias=bias is None, num_hidden=self._units,
            flatten=self._flatten,
        )
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (
            f"Dense({shape[1] if shape[1] else None} -> {shape[0]}, "
            f"{'linear' if self.act is None else self.act._act_type})"
        )


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with running stats (reference BatchNorm; op
    src/operator/nn/batch_norm.cc).  Running stats are grad_req='null'
    Parameters; the op returns the updated stats which we write back —
    functional state update instead of the reference's in-place aux-state
    mutation."""

    def __init__(self, axis=None, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        if axis is None:  # default follows the nn.default_layout scope
            from .layout import channel_axis
            axis = channel_axis()
        self._kwargs = {
            "axis": axis, "eps": epsilon, "momentum": momentum,
            "fix_gamma": not scale, "use_global_stats": use_global_stats,
        }
        self._axis = axis
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center,
            )
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False,
            )
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False,
            )

    def _infer_param_shapes(self, x, *args):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (channels,)

    def cast(self, dtype):
        if onp.dtype(dtype).name in ("float16", "bfloat16"):
            dtype = "float32"  # stats stay fp32 (reference semantics)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd

        training = (
            autograd.is_training()
            and not self._kwargs["use_global_stats"]
        )
        if training:
            out, batch_mean, batch_var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **self._kwargs
            )
            m = self._kwargs["momentum"]
            with autograd.pause():
                new_mean = m * running_mean + (1.0 - m) * batch_mean
                new_var = m * running_var + (1.0 - m) * batch_var
                # functional state write-back; under jit tracing the
                # HybridBlock harvests this as an extra program output
                running_mean._adopt(new_mean._data)
                running_var._adopt(new_var._data)
            return out
        return F.BatchNorm(
            x, gamma, beta, running_mean, running_var, **self._kwargs
        )

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return (
            f"BatchNorm(axis={self._axis}, eps={self._kwargs['eps']}, "
            f"momentum={self._kwargs['momentum']}, "
            f"in_channels={in_channels if in_channels else None})"
        )


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
            )

    def _infer_param_shapes(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon).swapaxes(
            1, self._axis
        )


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
            )

    def _infer_param_shapes(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(num_groups,), init=gamma_initializer,
                allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(num_groups,), init=beta_initializer,
                allow_deferred_init=True,
            )

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(
            x, gamma, beta, num_groups=self._num_groups, eps=self._epsilon
        )


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._dtype = dtype
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
            )

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(
            x, weight, input_dim=self._input_dim,
            output_dim=self._output_dim, dtype=self._dtype,
        )

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim}, {self._dtype})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function (or nd op name) as a Block."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            if not hasattr(nd, function):
                raise MXNetError(f"Function name {function} is not found in nd.")
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise MXNetError(
                "Unrecognized function in lambda: {} of type {}".format(
                    function, type(function)
                )
            )

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            if not hasattr(nd, function):
                raise MXNetError(f"Function name {function} is not found in nd.")
            self._func_name = function

            def _fn(F, *args):
                return getattr(F, function)(*args)

            self._func = _fn
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise MXNetError(
                "Unrecognized function in lambda: {} of type {}".format(
                    function, type(function)
                )
            )

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"


from .activations import Activation  # noqa: E402  (cycle: Dense uses it)
