"""Construction-time default-layout scope for conv/pool/norm layers.

The reference picks layout per-op via each operator's ``layout`` param
(src/operator/nn/convolution.cc) and its perf guide tells users to
switch the whole net (docs perf.md).  Here one scope flips every layer
default so a model builds channel-last end-to-end:

    with nn.default_layout("NHWC"):
        net = resnet50_v1()

Channel-last is the TPU-native layout — the channel dim sits on the
128-lane minor axis so XLA tiles convs straight onto the MXU with no
layout transposes.  Layers resolve their default at construction;
explicitly passed ``layout=``/``axis=`` always wins.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

# single source of truth for layout-string classification lives in the
# op layer (ops/conv.py); this module only adds the scoping mechanics
from ...ops.conv import _CHANNEL_FIRST, _CHANNEL_LAST

_state = threading.local()


def _current():
    return getattr(_state, "layout", "NCHW")


@contextmanager
def default_layout(layout):
    """Scope under which conv/pool/BatchNorm layer defaults follow
    ``layout`` ("NCHW"-family or "NHWC"-family; None = no change)."""
    if layout is None:
        yield
        return
    if layout not in _CHANNEL_LAST and layout not in _CHANNEL_FIRST:
        raise ValueError(f"unknown layout {layout!r}")
    prev = _current()
    _state.layout = layout
    try:
        yield
    finally:
        _state.layout = prev


def is_channel_last(layout=None):
    return (layout if layout is not None else _current()) in _CHANNEL_LAST


def resolve_layout(layout, ndim):
    """Layer-default layout for ``ndim`` spatial dims, honoring an
    explicit ``layout`` argument when given."""
    if layout is not None:
        return layout
    if is_channel_last():
        return ["NWC", "NHWC", "NDHWC"][ndim - 1]
    return ["NCW", "NCHW", "NCDHW"][ndim - 1]


def channel_axis(layout=None):
    """Channel axis for a 4-d activation under ``layout`` (or the scope
    default): 1 for channel-first, -1 for channel-last."""
    return -1 if is_channel_last(layout) else 1
