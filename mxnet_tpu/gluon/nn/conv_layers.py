"""Convolution / pooling blocks (reference: python/mxnet/gluon/nn/conv_layers.py).

Layer ``layout`` defaults resolve against the ambient
``nn.default_layout`` scope (channel-first NCHW-family, like the
reference, unless a scope says otherwise); the Convolution op lowers to
lax.conv_general_dilated which XLA tiles onto the MXU directly.
"""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ..block import HybridBlock
from .activations import Activation

__all__ = [
    "Conv1D", "Conv2D", "Conv3D",
    "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
    "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
    "ReflectionPad2D",
]


def _tup(val, n):
    if isinstance(val, (int, onp.integer)):
        return (int(val),) * n
    return tuple(int(v) for v in val)


class _Conv(HybridBlock):
    """Shared conv implementation (reference _Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution", adj=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        from .layout import is_channel_last, resolve_layout

        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        strides = _tup(strides, ndim)
        padding = _tup(padding, ndim)
        dilation = _tup(dilation, ndim)
        layout = resolve_layout(layout, ndim)
        self._channel_last = is_channel_last(layout)
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout,
        }
        if adj is not None:
            self._kwargs["adj"] = _tup(adj, ndim)
        self._layout = layout
        self._groups = groups

        with self.name_scope():
            cig = in_channels // groups if in_channels else 0
            if self._channel_last:
                # channel-last weight conventions (convolution.cc layout
                # param): conv O*kI, deconv I*kO
                if op_name == "Convolution":
                    wshape = (channels,) + tuple(kernel_size) + (cig,)
                else:
                    wshape = (in_channels,) + tuple(kernel_size) \
                        + (channels // groups,)
            elif op_name == "Convolution":
                wshape = (channels, cig) + tuple(kernel_size)
            else:  # Deconvolution: (in_channels, channels//groups, *k)
                wshape = (in_channels, channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True,
            )
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True,
                )
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _infer_param_shapes(self, x, *args):
        in_channels = x.shape[-1] if self._channel_last else x.shape[1]
        k = tuple(self._kwargs["kernel"])
        if self._channel_last:
            if self._op_name == "Convolution":
                self.weight.shape = (self._channels,) + k \
                    + (in_channels // self._groups,)
            else:
                self.weight.shape = (in_channels,) + k \
                    + (self._channels // self._groups,)
        elif self._op_name == "Convolution":
            self.weight.shape = (
                self._channels, in_channels // self._groups
            ) + k
        else:
            self.weight.shape = (
                in_channels, self._channels // self._groups
            ) + k
        self._in_channels = in_channels

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride}"
        len_kernel_size = len(self._kwargs["kernel"])
        if self._kwargs["pad"] != (0,) * len_kernel_size:
            s += ", padding={pad}"
        if self._kwargs["dilate"] != (1,) * len_kernel_size:
            s += ", dilation={dilate}"
        if self._groups != 1:
            s += ", groups={}".format(self._groups)
        if self.bias is None:
            s += ", bias=False"
        if self.act:
            s += ", {}".format(self.act)
        s += ")"
        shape = self.weight.shape
        cin = shape[-1] if self._channel_last else shape[1]
        return s.format(
            name=self.__class__.__name__,
            mapping="{0} -> {1}".format(cin if cin else None, shape[0]),
            **self._kwargs,
        )


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        if isinstance(kernel_size, (int, onp.integer)):
            kernel_size = (kernel_size,)
        if len(kernel_size) != 1:
            raise MXNetError("kernel_size must be 1 int for Conv1D")
        super().__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _tup(kernel_size, 2)
        if len(kernel_size) != 2:
            raise MXNetError("kernel_size must be 2 ints for Conv2D")
        super().__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _tup(kernel_size, 3)
        if len(kernel_size) != 3:
            raise MXNetError("kernel_size must be 3 ints for Conv3D")
        super().__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class _ConvTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides, padding,
                 output_padding, dilation, groups, layout, in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution",
            adj=output_padding, **kwargs)
        self.outpad = output_padding


class Conv1DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout=None,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        if isinstance(kernel_size, (int, onp.integer)):
            kernel_size = (kernel_size,)
        super().__init__(
            channels, kernel_size, strides, padding, output_padding,
            dilation, groups, layout, in_channels, activation, use_bias,
            weight_initializer, bias_initializer, **kwargs)


class Conv2DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _tup(kernel_size, 2)
        super().__init__(
            channels, kernel_size, strides, padding, output_padding,
            dilation, groups, layout, in_channels, activation, use_bias,
            weight_initializer, bias_initializer, **kwargs)


class Conv3DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout=None,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _tup(kernel_size, 3)
        super().__init__(
            channels, kernel_size, strides, padding, output_padding,
            dilation, groups, layout, in_channels, activation, use_bias,
            weight_initializer, bias_initializer, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 global_pool=False, pool_type="max", layout=None,
                 count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        from .layout import resolve_layout

        if strides is None:
            strides = pool_size
        ndim = len(pool_size)
        self._kwargs = {
            "kernel": pool_size, "stride": _tup(strides, ndim),
            "pad": _tup(padding, ndim), "global_pool": global_pool,
            "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": resolve_layout(layout, ndim),
        }
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (
            "{name}(size={kernel}, stride={stride}, padding={pad}, "
            "ceil_mode={ceil_mode})".format(
                name=self.__class__.__name__,
                ceil_mode=self._kwargs["pooling_convention"] == "full",
                **self._kwargs,
            )
        )


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(
            _tup(pool_size, 1), strides, padding, ceil_mode, False, "max",
            layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, **kwargs):
        super().__init__(
            _tup(pool_size, 2), strides, _tup(padding, 2), ceil_mode, False,
            "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, **kwargs):
        super().__init__(
            _tup(pool_size, 3), strides, _tup(padding, 3), ceil_mode, False,
            "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(
            _tup(pool_size, 1), strides, padding, ceil_mode, False, "avg",
            layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(
            _tup(pool_size, 2), strides, _tup(padding, 2), ceil_mode, False,
            "avg", layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(
            _tup(pool_size, 3), strides, _tup(padding, 3), ceil_mode, False,
            "avg", layout, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, 0, True, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, 0, True, True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "max", layout,
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, 0, True, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, 0, True, True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "avg", layout,
                         **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, (int, onp.integer)):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
