"""Parameter / ParameterDict — Gluon's weight containers.

Reference parity: python/mxnet/gluon/parameter.py (``Parameter`` with
deferred init, grad_req plumbing, per-context copies; ``ParameterDict``
prefix-scoped registry).  TPU-native redesign: one logical copy of each
parameter as an NDArray over a jax.Array — replication/sharding across
chips is an XLA sharding annotation applied by the Trainer/parallel layer,
not N explicit per-device copies (reference keeps `_ctx_list` arrays;
here `list_ctx` reports the devices of the underlying jax.Array).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as onp

from .. import initializer as init_mod
from .. import ndarray as nd
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import InitDesc

__all__ = ["DeferredInitializationError", "Parameter", "Constant", "ParameterDict"]


class DeferredInitializationError(MXNetError):
    """Parameter used before its shape is known (reference parameter.py)."""


def _strip_arg_aux(loaded):
    """Exported checkpoints key params as 'arg:<name>'/'aux:<name>'
    (reference export convention) — strip for matching."""
    if any(k.startswith(("arg:", "aux:")) for k in loaded):
        return {k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                else k: v for k, v in loaded.items()}
    return loaded


class Parameter:
    """A weight tensor with autograd + initialization state.

    Matches the reference's API surface: ``initialize``, ``data``,
    ``grad``, ``set_data``, ``zero_grad``, ``var``, ``cast``,
    ``shape``/``dtype``/``grad_req`` mutability and deferred init (shape
    with 0s resolved at first forward).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = None
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None  # NDArray
        self._deferred_init = None  # (init, ctx, default_init)
        self.grad_req = grad_req
        self._attributes = {}

    # ---------------------------------------------------------------- attrs
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"grad_req must be write/add/null, got {req}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape) if new_shape is not None else None
            return
        if new_shape is None:
            return
        unknown_ok = all(
            s1 in (0, s2) for s1, s2 in zip(self._shape, new_shape)
        ) and len(self._shape) == len(new_shape)
        if not unknown_ok:
            raise MXNetError(
                f"Expected shape {new_shape} is incompatible with given "
                f"shape {self._shape} for Parameter {self.name}"
            )
        self._shape = tuple(new_shape)

    def _shape_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    # ------------------------------------------------------------- lifecycle
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if not self._shape_known():
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"Cannot initialize Parameter {self.name} because it has "
                f"invalid shape: {self._shape}."
            )
        self._finish_init(init, ctx, default_init)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        init, ctx, default_init = self._deferred_init
        if not self._shape_known():
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape}"
            )
        self._deferred_init = None
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        initializer = init if init is not None else (self.init or default_init)
        initializer = init_mod.create(initializer) if not callable(
            initializer
        ) or isinstance(initializer, init_mod.Initializer) else initializer
        if isinstance(initializer, init_mod.Initializer) or callable(initializer):
            value = initializer(InitDesc(self.name), self._shape, self.dtype)
        else:  # pragma: no cover
            raise MXNetError(f"bad initializer for {self.name}")
        arr = nd.array(onp.asarray(value), ctx=ctx[0], dtype=self.dtype)
        self._data = arr
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        if self._data is None:
            return
        self._data.attach_grad(grad_req=self._grad_req)

    # ----------------------------------------------------------------- data
    def _check_init(self):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not been initialized yet "
                    "because initialization was deferred. Actual "
                    "initialization happens during the first forward pass."
                )
            raise MXNetError(
                f"Parameter {self.name} has not been initialized. You "
                "should initialize parameters with Block.initialize()."
            )

    def data(self, ctx=None):
        self._check_init()
        return self._data

    def list_data(self):
        self._check_init()
        return [self._data]

    def grad(self, ctx=None):
        self._check_init()
        if self._data._grad is None:
            raise MXNetError(
                f"Cannot get gradient array for Parameter {self.name} "
                "because grad_req='null'"
            )
        return self._data._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None and self._deferred_init is not None:
            return self._deferred_init[1]
        self._check_init()
        return [self._data.context]

    def set_data(self, data):
        self.shape = tuple(data.shape)
        if self._data is None:
            if self._deferred_init is None:
                raise MXNetError(
                    f"Parameter {self.name} has not been initialized"
                )
            init, ctx, default_init = self._deferred_init
            self._deferred_init = None
            self._finish_init(init_mod.Constant(0), ctx, default_init)
        if not isinstance(data, nd.NDArray):
            data = nd.array(data, dtype=self.dtype)
        new = data.astype(self.dtype)._data
        # keep the parameter on its current device: loading .params from
        # disk (host arrays) must not silently migrate a TPU-resident
        # parameter back to CPU (reference set_data keeps ctx)
        cur = self._data._data
        if hasattr(cur, "devices") and hasattr(new, "devices") \
                and cur.devices() != new.devices():
            import jax
            # target the existing sharding (covers multi-device/mesh
            # placements), not just one device of it
            new = jax.device_put(new, cur.sharding)
        self._data._adopt(new)

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            g = self._data._grad
            g._adopt(nd.zeros(g.shape, dtype=g.dtype)._data)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = self._data.astype(dtype)
            if had_grad:
                self._init_grad()

    def reset_ctx(self, ctx):
        pass  # single logical copy; sharding is a compiler annotation

    def var(self):
        from .. import symbol
        return symbol.var(
            self.name, shape=self.shape, dtype=self.dtype,
            lr_mult=self.lr_mult, wd_mult=self.wd_mult,
        )

    def __repr__(self):
        return (
            f"Parameter {self.name} (shape={self._shape}, "
            f"dtype={self.dtype})"
        )


class Constant(Parameter):
    """Non-trainable parameter holding a constant (reference Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, nd.NDArray):
            value = nd.array(onp.asarray(value))
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(_self, _name, _shape):
                return value.asnumpy()

        super().__init__(
            name, grad_req="null", shape=value.shape, dtype=value.dtype,
            init=_CInit(), differentiable=False,
        )


class ParameterDict:
    """Prefix-scoped ordered dict of Parameters (reference ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, key):
        return self._params[key]

    def __repr__(self):
        lines = [f"{self._prefix or 'ParameterDict'} ("]
        lines += [f"  {v!r}" for v in self._params.values()]
        lines.append(")")
        return "\n".join(lines)

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Get-or-create ``self.prefix + name`` (reference get())."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
            return param
        for k, v in kwargs.items():
            if k == "shape":
                param.shape = v
            elif getattr(param, k, None) is None or k in ("init",):
                setattr(param, k, v)
            else:
                existing = getattr(param, k)
                if k == "dtype":
                    same = onp.dtype(existing) == onp.dtype(v)
                else:
                    same = existing == v
                if not same:
                    # reference parameter.py get() asserts existing
                    # attributes match a re-declaration
                    raise MXNetError(
                        f"Parameter '{name}' already exists with "
                        f"{k}={existing!r}, but the request specifies "
                        f"{k}={v!r}.")
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(
                    f"No constant named '{name}'. Please specify value."
                )
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(
                    f"Cannot update self with other because they have "
                    f"different Parameters with the same name '{k}'"
                )
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        for v in self.values():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = sum(w.copyto(cpu()) for w in block) / len(block)
            if not param.name.startswith(strip_prefix):
                raise MXNetError(
                    f"Prefix '{strip_prefix}' is to be stripped before "
                    f"saving, but Parameter's name '{param.name}' does not "
                    "start with it"
                )
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = _strip_arg_aux(nd.load(filename))
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXNetError(
                        f"Parameter '{name}' is missing in file '{filename}'"
                    )
        for name, arr in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(
                        f"Parameter '{name}' loaded from file "
                        f"'{filename}' is not present in this ParameterDict"
                    )
                continue
            param = self._params[name]
            if param._data is None and param._deferred_init is not None:
                param.shape = tuple(arr.shape)
            elif param._data is None:
                param.shape = tuple(arr.shape)
                param.initialize(ctx=ctx)
            param.set_data(arr)
