"""RNN / LSTM / GRU layers over the fused RNN op.

Reference parity: python/mxnet/gluon/rnn/rnn_layer.py (``_RNNLayer`` ->
fused ``RNN`` op, src/operator/rnn-inl.h).  Parameters are kept per
(layer, direction) like the reference ({l,r}{i}_{i2h,h2h}_{weight,bias})
and packed into the fused flat vector at forward time — the pack is pure
concatenation so XLA folds it away.
"""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"Invalid layout {layout}; must be TNC or NTC")
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        if projection_size is not None and mode != "lstm":
            raise MXNetError("projection_size is LSTM-only "
                             "(reference rnn-inl.h:444)")

        self._gates = _GATES[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        nr = projection_size if projection_size else nh
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][: self._dir]:
                    self._register_param(
                        f"{j}{i}_i2h_weight", (ng * nh, ni),
                        i2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_weight", (ng * nh, nr),
                        h2h_weight_initializer)
                    if projection_size:
                        self._register_param(
                            f"{j}{i}_h2r_weight", (nr, nh),
                            h2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_i2h_bias", (ng * nh,),
                        i2h_bias_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_bias", (ng * nh,),
                        h2h_bias_initializer)
                ni = nr * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(
            name, shape=shape, init=init, allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def _infer_param_shapes(self, x, *args):
        ins = x.shape[2]  # C is axis 2 in both TNC and NTC
        ng, nh = self._gates, self._hidden_size
        nr = self._projection_size if self._projection_size else nh
        ni = ins
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = nr * self._dir
        self._input_size = ins

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], **kwargs)
                          if "shape" in info else func(**kwargs))
        return states

    def cast(self, dtype):
        super().cast(dtype)

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = [
                F.zeros(info["shape"], dtype=str(inputs.dtype))
                for info in self.state_info(batch_size)
            ]
        if not isinstance(states, (list, tuple)):
            states = [states]

        # pack per-layer params into the fused flat vector (weights then
        # biases, layer-major, direction-minor — rnn.py layout)
        flat = []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                flat.append(params[f"{j}{i}_i2h_weight"].reshape((-1,)))
                flat.append(params[f"{j}{i}_h2h_weight"].reshape((-1,)))
                if self._projection_size:
                    flat.append(
                        params[f"{j}{i}_h2r_weight"].reshape((-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                flat.append(params[f"{j}{i}_i2h_bias"])
                flat.append(params[f"{j}{i}_h2h_bias"])
        packed = F.concat(*flat, dim=0)

        rnn_args = [inputs, packed] + list(states)
        out = F.RNN(
            *rnn_args,
            state_size=self._hidden_size,
            num_layers=self._num_layers,
            bidirectional=self._dir == 2,
            p=self._dropout,
            state_outputs=True,
            mode=self._mode,
            projection_size=self._projection_size,
        )
        if self._mode == "lstm":
            outputs, states = out[0], [out[1], out[2]]
        else:
            outputs, states = out[0], [out[1]]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, 0, 1)
        if skip_states:
            return outputs
        return outputs, states

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        mapping = "{0} -> {1}".format(
            self._input_size if self._input_size else None, self._hidden_size
        )
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)


class RNN(_RNNLayer):
    """Vanilla multi-layer Elman RNN (tanh or relu)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer,
            "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{
            "shape": (self._num_layers * self._dir, batch_size,
                      self._hidden_size),
            "__layout__": "LNC",
        }]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "lstm",
            projection_size, **kwargs)

    def state_info(self, batch_size=0):
        # h state uses the projected size under LSTMP; c keeps H
        r = self._projection_size or self._hidden_size
        return [
            {"shape": (self._num_layers * self._dir, batch_size, r),
             "__layout__": "LNC"},
            {"shape": (self._num_layers * self._dir, batch_size,
                       self._hidden_size), "__layout__": "LNC"},
        ]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{
            "shape": (self._num_layers * self._dir, batch_size,
                      self._hidden_size),
            "__layout__": "LNC",
        }]
