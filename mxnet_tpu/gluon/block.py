"""Block / HybridBlock — the Gluon imperative layer API.

Reference parity: python/mxnet/gluon/block.py (``Block`` :228 with child
registry + param collection, ``HybridBlock`` :838 whose ``hybridize()``
:1039 builds a ``CachedOp`` :969 executing the traced graph).

TPU-native redesign: ``hybridize()`` wraps the block's forward in
``jax.jit``.  The jitted callable takes (params..., inputs..., prng key)
as explicit jax arrays and is differentiated as ONE tape node via
``jax.vjp`` — exactly the role of the reference's ``_CachedOp`` node in
autograd (src/imperative/cached_op.cc:1023/:1249).  ``static_alloc`` maps
to buffer donation; ``static_shape`` is implicit (XLA recompiles per
shape signature, cached — reference CachedOp re-infers shapes per call).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as onp

from .. import _rng, autograd
from .. import ndarray as nd
from ..base import MXNetError
from ..context import current_context
from .parameter import DeferredInitializationError, Parameter, ParameterDict

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _SuppressHooks(threading.local):
    """Set during internal forward passes (deferred-shape resolution) so
    user-registered hooks only observe real user-initiated forwards."""

    def __init__(self):
        self.flag = False


_suppress_hooks = _SuppressHooks()


class _BlockScope(threading.local):
    """Name-scope manager producing reference-compatible prefixes."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_mgr().get(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class _NameManager(threading.local):
    def __init__(self):
        self._counter = {}

    def get(self, hint):
        count = self._counter.get(hint, 0)
        self._counter[hint] = count + 1
        return f"{hint}{count}"


_NM = _NameManager()


def _name_mgr():
    return _NM


def _flatten_to_nd(args):
    """Flatten nested (list/tuple) structure of NDArrays; returns flat list
    and a treedef-like spec for unflatten."""
    flat, fmt = [], []
    for a in args:
        if isinstance(a, nd.NDArray):
            flat.append(a)
            fmt.append(-1)
        elif isinstance(a, (list, tuple)):
            sub_flat, sub_fmt = _flatten_to_nd(a)
            flat.extend(sub_flat)
            fmt.append((len(sub_flat), sub_fmt, isinstance(a, tuple)))
        else:
            flat.append(a)
            fmt.append(-2)
    return flat, fmt


def _unflatten(flat, fmt):
    out = []
    i = 0
    for f in fmt:
        if f == -1 or f == -2:
            out.append(flat[i])
            i += 1
        else:
            n, sub_fmt, is_tuple = f
            sub, _ = _unflatten(flat[i : i + n], sub_fmt), None
            out.append(tuple(sub[0]) if is_tuple else sub[0])
            i += n
    return out, None


class Block:
    """Base class for all layers/models (reference gluon/block.py:228)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias()
        )
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    # ------------------------------------------------------------ registry
    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(
                value, type(existing)
            ):
                raise MXNetError(
                    f"Changing attribute type for {getattr(self, 'name', '?')} "
                    f"is not allowed."
                )
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        key = len(self._forward_hooks)
        self._forward_hooks[key] = hook
        return _HookHandle(self._forward_hooks, key)

    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return _HookHandle(self._forward_pre_hooks, key)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All Parameters of self + descendants, optionally regex-filtered
        (reference block.py collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update(
                {k: v for k, v in self.params.items() if pattern.match(k)}
            )
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    # ---------------------------------------------------------- lifecycle
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -------------------------------------------------------------- io
    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        nd.save(filename, {k: p.data() for k, p in params.items()})

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from .parameter import _strip_arg_aux

        loaded = _strip_arg_aux(nd.load(filename))
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in k for k in loaded.keys()):
            # legacy full-name format -> load via ParameterDict
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix
            )
            return
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise MXNetError(
                        f"Parameter '{name}' is missing in file '{filename}'"
                    )
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise MXNetError(
                        f"Parameter '{name}' loaded from file '{filename}' "
                        "is not present in this Block"
                    )
                continue
            param = params[name]
            arr = loaded[name]
            if param._data is None:
                param.shape = tuple(arr.shape)
                if param._deferred_init is None:
                    param.initialize(ctx=ctx)
            param.set_data(arr)

    save_params = save_parameters
    load_params = load_parameters

    # ------------------------------------------------------------- forward
    def __call__(self, *args):
        if _suppress_hooks.flag:
            return self.forward(*args)
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        summary = OrderedDict()
        seen = set()
        hooks = []

        def _register(block, prefix):
            def _hook(blk, ins, outs):
                name = prefix or blk.name
                out0 = outs[0] if isinstance(outs, (list, tuple)) else outs
                n_params = 0
                for p in blk._reg_params.values():
                    if p._shape_known():
                        n_params += int(onp.prod(p.shape))
                summary[name] = (
                    blk.__class__.__name__,
                    getattr(out0, "shape", None),
                    n_params,
                )

            hooks.append(block.register_forward_hook(_hook))
            for cname, child in block._children.items():
                _register(child, (prefix + "." if prefix else "") + cname)

        _register(self, "")
        try:
            self(*inputs)
        finally:
            for h in hooks:
                h.detach()
        lines = [f"{'Layer':<40}{'Output Shape':<24}{'Param #':<12}"]
        lines.append("=" * 76)
        total = 0
        for name, (cls, shape, n) in summary.items():
            lines.append(f"{cls + ' (' + name + ')':<40}{str(shape):<24}{n:<12}")
            total += n
        lines.append("=" * 76)
        lines.append(f"Total params: {total}")
        print("\n".join(lines))

    def __repr__(self):
        lines = [self.__class__.__name__ + "("]
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        lines.append(")")
        return "\n".join(lines)


class _HookHandle:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def detach(self):
        self._hooks.pop(self._key, None)


class HybridBlock(Block):
    """Block whose forward is expressible as a pure function of inputs +
    params — hybridizable to one compiled XLA program.

    Subclasses implement ``hybrid_forward(F, x, *, weight=..., ...)``
    where F is the ``nd`` (or ``symbol``) namespace, exactly like the
    reference.  Registered parameters are passed as kwargs.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._jit_cache = {}
        self._flags = {}
        self._partial_shaping = False

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._flags = dict(
            static_alloc=static_alloc, static_shape=static_shape, **kwargs
        )
        self._clear_cached_op()
        # children keep running imperatively inside the parent's trace
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child._clear_cached_op()

    def _clear_cached_op(self):
        self._jit_cache = {}

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from example inputs."""
        self._infer_and_init(*args)

    # ------------------------------------------------------------- forward
    def forward(self, x, *args):
        from .. import symbol as _sym_mod

        if isinstance(x, _sym_mod.Symbol):
            # symbolic trace (reference: hybrid_forward with F=mx.sym):
            # parameters appear as named variables so the exported graph
            # aligns with collect_params()/save_parameters names
            params = {k: v.var() for k, v in self._reg_params.items()}
            return self.hybrid_forward(_sym_mod, x, *args, **params)
        if isinstance(x, nd.NDArray) and not isinstance(
            x._data, jax.core.Tracer
        ) and self._active:
            return self._call_cached(x, *args)
        # imperative path (also the trace path when _data is a tracer)
        try:
            params = {k: v.data() for k, v in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_param_shapes(x, *args)
            for _, p in self._reg_params.items():
                p._finish_deferred_init()
            params = {k: v.data() for k, v in self._reg_params.items()}
        return self.hybrid_forward(nd, x, *args, **params)

    def _infer_and_init(self, *args):
        """Resolve deferred shapes across the whole subtree by running one
        eager (non-jit) forward; each leaf layer fills its own shapes via
        ``_infer_param_shapes`` when first called.  Reference analog:
        deferred shape inference in block.py _build_cache/infer_shape."""
        states = []

        def _disable(b):
            if isinstance(b, HybridBlock):
                states.append((b, b._active))
                b._active = False
            for c in b._children.values():
                _disable(c)

        _disable(self)
        prev = _suppress_hooks.flag
        _suppress_hooks.flag = True  # internal pass: no user hooks
        try:
            with autograd.pause():
                Block.__call__(self, *args)
        finally:
            _suppress_hooks.flag = prev
            for b, s in states:
                b._active = s

    def _infer_param_shapes(self, *args):
        """Subclasses with deferred shapes override (e.g. Dense infers
        in_units from input)."""
        raise DeferredInitializationError(
            f"{self.name}: parameter shapes unknown and block does not "
            "implement shape inference"
        )

    @staticmethod
    def _donating_fn(entry, pdata, idata):
        """Donating twin of the cached jit program (``static_alloc``
        memory reuse): parameters the traced program MUTATES (BatchNorm
        moving stats adopted a new value — ``upd_idx`` in the entry
        meta) are passed as a separate donated argument, so XLA writes
        each update into its input's buffer instead of allocating.
        Returns None (caller uses the plain program) until the first
        call has populated the meta, when mutation is absent, when
        donation is disabled, or when a mutated buffer is aliased into
        a non-donated slot (shared parameters)."""
        from .. import config as _config

        meta = entry["meta"]
        if meta is None or not meta[3] or \
                not _config.get_env("MXNET_EXEC_DONATE"):
            return None
        upd_idx = meta[3]
        upd_set = set(upd_idx)
        upd_ids = {id(pdata[i]) for i in upd_idx}
        aliased = any(id(v) in upd_ids for i, v in enumerate(pdata)
                      if i not in upd_set)
        aliased = aliased or any(v is not None and id(v) in upd_ids
                                 for v in idata)
        if aliased:
            return None
        if entry.get("fn_d") is None:
            run = entry["run"]

            def _run_d(key, upd_vals, rest_vals, in_vals):
                pv = list(rest_vals)
                for j, i in enumerate(upd_idx):
                    pv[i] = upd_vals[j]
                return run(key, pv, in_vals)

            entry["fn_d"] = jax.jit(_run_d, donate_argnums=(1,))
        return entry["fn_d"]

    def _telemetry_trace(self, sig, training, plat, probe, _at):
        """One compile record per new CachedOp jit entry.  No-op when
        MXNET_RUNLOG is unset (one call + dict lookup); the RunLog
        diffs this fingerprint against the program's previous one to
        name the retrace cause (shape/dtype/train_mode/
        autotune_winner)."""
        from .. import telemetry

        rl = telemetry.current()
        if rl is None:
            return
        shapes, train = sig
        try:
            winners = {}
            if probe is not None and _at.enabled():
                winners = {op: _at.lookup(op, probe.shape, probe.dtype,
                                          platform=plat)
                           for op in _at.VARIANT_OPS}
            rl.compile_event(
                f"cachedop:{self.name}",
                telemetry.compile_fingerprint(
                    [s[0] for s in shapes if s[0] != "#py"],
                    [s[1] for s in shapes if s[0] != "#py"],
                    train, winners=winners))
        except Exception:
            pass  # telemetry must never kill a forward

    def _call_cached(self, *args):
        """jit path: one compiled program, one autograd tape node.

        The traced callable swaps every subtree Parameter's value for a
        traced jax value, runs the ordinary imperative forward (children
        included), and returns the flat outputs — the analog of
        CachedOp::Forward executing the cached graph
        (src/imperative/cached_op.cc:1023)."""
        flat_in, fmt = _flatten_to_nd(args)
        try:
            all_params = _collect_all_params(self)
            pdata = [p.data()._data for p in all_params]
        except DeferredInitializationError:
            self._infer_and_init(*args)
            all_params = _collect_all_params(self)
            pdata = [p.data()._data for p in all_params]
        training = autograd.is_training()
        sig = (
            tuple(
                (a.shape, str(a.dtype)) if isinstance(a, nd.NDArray)
                else ("#py", repr(a))
                for a in flat_in
            ),
            training,
        )
        entry = self._jit_cache.get(sig)
        new_entry = entry is None
        if entry is None:
            entry = {"meta": None}
            # capture only non-array (python) inputs; array slots are fed
            # through in_vals so no device buffers pin in the closure
            py_slots = {
                i: a for i, a in enumerate(flat_in)
                if not isinstance(a, nd.NDArray)
            }

            def _run(key, param_vals, in_vals):
                with _rng.trace_key_scope(key), autograd._Scope(
                    False, training
                ):
                    saved = _swap_param_values(self, param_vals)
                    try:
                        arrs = [
                            nd.NDArray(v) if v is not None
                            else py_slots[i]
                            for i, v in enumerate(in_vals)
                        ]
                        rebuilt, _ = _unflatten(arrs, fmt)
                        out = Block.__call__(self, *rebuilt)
                        # state mutations (e.g. BatchNorm running stats
                        # adopted a new traced value) become extra outputs
                        flat_params = _collect_all_params(self)
                        upd_idx, upd_vals = [], []
                        for i, p in enumerate(flat_params):
                            cur = p._data._data
                            if cur is not param_vals[i]:
                                upd_idx.append(i)
                                upd_vals.append(cur)
                    finally:
                        _swap_param_values(self, saved)
                single = not isinstance(out, (list, tuple))
                flat_out, out_fmt = _flatten_to_nd([out] if single else out)
                entry["meta"] = (out_fmt, single, len(flat_out),
                                 tuple(upd_idx))
                return tuple(o._data for o in flat_out) + tuple(upd_vals)

            entry["fn"] = jax.jit(_run)
            entry["run"] = _run  # donating twin builds lazily from it
            self._jit_cache[sig] = entry

        jitted = entry["fn"]
        key = _rng.take_key()
        idata = [
            a._data if isinstance(a, nd.NDArray) else None for a in flat_in
        ]

        def _tracked(x):
            return x._is_var or x._node is not None

        # trace-platform hint for kernel-backed ops (ops/pallas_conv):
        # jax traces are platform-agnostic, so ops choosing between a
        # Pallas kernel and plain jnp need to know where THIS program's
        # concrete arguments live
        from .. import autotune as _at
        from ..ops import pallas_conv as _pc

        plat = _pc.platform_of(pdata) or _pc.platform_of(idata)
        _hint_prev = _pc.set_trace_platform(plat)
        # autotuned variant winners for this program's input signature
        # apply while the cached program traces (cudnn algo registry
        # consulted at CachedOp::Forward graph build)
        _probe = next((a for a in flat_in if isinstance(a, nd.NDArray)),
                      None)
        _scope = _at.program_scope(
            _probe.shape if _probe is not None else (),
            _probe.dtype if _probe is not None else "none",
            platform=plat)
        _scope.__enter__()
        if new_entry:
            # one compile record per new CachedOp program (the gluon
            # jit path's retrace observer, mirroring Executor's) —
            # the RunLog diffs the fingerprint to name the cause
            self._telemetry_trace(sig, training, plat, _probe, _at)
        try:
            nd_params = [p.data() for p in all_params]
            recording = autograd.is_recording() and (
                any(_tracked(p) for p in nd_params)
                or any(
                    isinstance(a, nd.NDArray) and _tracked(a)
                    for a in flat_in
                )
            )
            if recording:
                def _f(ps, xs):
                    return jitted(key, ps, xs)

                out_vals, vjp_fn = jax.vjp(_f, pdata, idata)

                def _pullback(cots):
                    if not isinstance(cots, tuple):
                        cots = (cots,)
                    # the custom-vjp bwd rules trace HERE (first
                    # backward), so the platform hint must be live
                    prev = _pc.set_trace_platform(plat)
                    try:
                        gp, gx = vjp_fn(cots)
                    finally:
                        _pc.set_trace_platform(prev)
                    return list(gp) + list(gx)

                node = autograd.TapeNode(
                    _pullback,
                    [p if _tracked(p) else None for p in nd_params]
                    + [
                        a if isinstance(a, nd.NDArray) and _tracked(a)
                        else None
                        for a in flat_in
                    ],
                    [(tuple(map(int, v.shape)), v.dtype)
                     for v in out_vals],
                    op_name=f"jit:{self.name}",
                )
                outs = []
                for i, v in enumerate(out_vals):
                    o = nd.NDArray(v)
                    o._node = node
                    o._oidx = i
                    outs.append(o)
            else:
                fn_d = self._donating_fn(entry, pdata, idata)
                if fn_d is not None:
                    upd_idx = entry["meta"][3]
                    upd_set = set(upd_idx)
                    upd_vals = [pdata[i] for i in upd_idx]
                    rest = [None if i in upd_set else v
                            for i, v in enumerate(pdata)]
                    out_vals = fn_d(key, upd_vals, rest, idata)
                else:
                    out_vals = jitted(key, pdata, idata)
                outs = [nd.NDArray(v) for v in out_vals]
        finally:
            _scope.__exit__(None, None, None)
            _pc.set_trace_platform(_hint_prev)

        out_fmt, single, n_primary, upd_idx = entry["meta"]
        if upd_idx:
            for i, v in zip(upd_idx, out_vals[n_primary:]):
                all_params[i]._data._adopt(v)
            outs = outs[:n_primary]
        rebuilt, _ = _unflatten(outs, out_fmt)
        return rebuilt[0] if single else rebuilt

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Write ``path-symbol.json`` + ``path-{epoch:04d}.params``
        (reference block.py export): the graph comes from a symbolic
        trace of hybrid_forward, parameters are saved under the
        reference's ``arg:``/``aux:`` key convention so
        ``SymbolBlock.imports``/``mx.mod.Module`` can load them."""
        from .. import ndarray as _ndm
        from .. import symbol as _sym_mod

        data = _sym_mod.var("data")
        out = self(data)
        if isinstance(out, (list, tuple)):
            out = _sym_mod.Group(list(out))
        out.save(f"{path}-symbol.json")
        # arg/aux split follows the GRAPH's classification (__aux__
        # marking == nnvm mutable inputs), not grad_req: a frozen
        # trainable weight is still an arg
        aux_names = set(out.list_auxiliary_states())
        arg_aux = {}
        for name, p in self.collect_params().items():
            kind = "aux" if name in aux_names else "arg"
            arg_aux[f"{kind}:{name}"] = p.data()
        _ndm.save(f"{path}-{epoch:04d}.params", arg_aux)
        return out


def _collect_all_params(block):
    """Flat list of subtree Parameters in deterministic registry order —
    the order used both for jit inputs and for value swapping."""
    result = list(block._reg_params.values())
    for child in block._children.values():
        result.extend(_collect_all_params(child))
    return result


def _swap_param_values(block, values):
    """Temporarily rebind every subtree Parameter's jax value to the traced
    values (same flat order as _collect_all_params); returns the saved
    originals so the caller can restore after tracing."""
    flat = _collect_all_params(block)
    saved = []
    for p, v in zip(flat, values):
        arr = p._data
        saved.append(arr._data)
        arr._data = v
    return saved


class SymbolBlock(HybridBlock):
    """Construct a Block from a symbolic graph (lands fully with mx.sym;
    reference gluon/block.py:1190)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._outputs = outputs
        self._inputs = inputs
        # every non-input graph variable becomes a Parameter (aux vars
        # with grad_req='null'), so load_parameters/collect_params see
        # the full weight set (reference block.py:1236)
        input_names = {s.name for s in inputs}
        aux = set(outputs.list_auxiliary_states()) \
            if hasattr(outputs, "list_auxiliary_states") else set()
        for name in outputs.list_inputs():
            if name in input_names:
                continue
            self.params.get(
                name, grad_req="null" if name in aux else "write",
                allow_deferred_init=True, differentiable=name not in aux)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod

        symbol = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        ret = SymbolBlock(symbol, inputs)
        if param_file is not None:
            ret.load_parameters(param_file, ctx=ctx, cast_dtype=True)
        return ret

    def forward(self, *args):
        arg_dict = {s.name: a for s, a in zip(self._inputs, args)}
        aux_names = set(self._outputs.list_auxiliary_states()) \
            if hasattr(self._outputs, "list_auxiliary_states") else set()
        arg_params, aux_params = {}, {}
        for name, p in self.collect_params().items():
            (aux_params if name in aux_names else arg_params)[name] = \
                p.data()
        ex = self._outputs.bind(args={**arg_dict, **arg_params},
                                aux_states=aux_params)
        outs = ex.forward()
        return outs[0] if len(outs) == 1 else outs
