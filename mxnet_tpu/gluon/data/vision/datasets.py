"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py).

MNIST/FashionMNIST read the standard IDX files; CIFAR10/100 read the
binary batches; ImageRecordDataset/ImageFolderDataset over local files.
Zero-egress environment: datasets are read from `root`, never downloaded.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as onp

from .... import ndarray as nd
from .... import recordio
from ....base import MXNetError
from ..dataset import Dataset, RecordFileDataset, _DownloadedDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic = struct.unpack(">I", data[:4])[0]
    ndim = magic & 0xFF
    dims = struct.unpack(">" + "I" * ndim, data[4 : 4 + 4 * ndim])
    arr = onp.frombuffer(data[4 + 4 * ndim:], dtype=onp.uint8)
    return arr.reshape(dims)


class MNIST(_DownloadedDataset):
    """MNIST from IDX files in `root` (reference gluon MNIST)."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _find(self, base):
        for cand in (base, base + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.isfile(p):
                return p
        raise MXNetError(
            f"{base} not found under {self._root}; this environment has "
            "no network egress — place the IDX files there manually.")

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        images = _read_idx(self._find(files[0]))
        labels = _read_idx(self._find(files[1]))
        self._data = nd.array(
            images.reshape(-1, 28, 28, 1).astype(onp.uint8), dtype="uint8")
        self._label = labels.astype(onp.int32)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the binary batches in `root`."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None):
        self._train = train
        self._archive_subdir = "cifar-10-batches-bin"
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = onp.frombuffer(fin.read(), dtype=onp.uint8).reshape(
                -1, 3072 + 1)
        return (
            data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1),
            data[:, 0].astype(onp.int32))

    def _get_data(self):
        base = self._root
        sub = os.path.join(base, self._archive_subdir)
        if os.path.isdir(sub):
            base = sub
        if self._train:
            filenames = [os.path.join(base, f"data_batch_{i}.bin")
                         for i in range(1, 6)]
        else:
            filenames = [os.path.join(base, "test_batch.bin")]
        for f in filenames:
            if not os.path.isfile(f):
                raise MXNetError(
                    f"{f} not found; no network egress — place CIFAR "
                    "binary batches there manually.")
        data, label = zip(*[self._read_batch(f) for f in filenames])
        self._data = nd.array(onp.concatenate(data), dtype="uint8")
        self._label = onp.concatenate(label)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        self._train = train
        self._archive_subdir = "cifar-100-binary"
        _DownloadedDataset.__init__(self, root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = onp.frombuffer(fin.read(), dtype=onp.uint8).reshape(
                -1, 3072 + 2)
        return (
            data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1),
            data[:, 0 + self._fine_label].astype(onp.int32))

    def _get_data(self):
        base = self._root
        sub = os.path.join(base, self._archive_subdir)
        if os.path.isdir(sub):
            base = sub
        name = "train.bin" if self._train else "test.bin"
        f = os.path.join(base, name)
        if not os.path.isfile(f):
            raise MXNetError(f"{f} not found (no network egress)")
        data, label = self._read_batch(f)
        self._data = nd.array(data, dtype="uint8")
        self._label = label


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a .rec file (reference ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        record = super().__getitem__(idx)
        header, img_bytes = recordio.unpack(record)
        img = recordio._imdecode(
            onp.frombuffer(img_bytes, dtype=onp.uint8), self._flag)
        img = nd.array(img, dtype="uint8")
        if self._transform is not None:
            return self._transform(img, header.label)
        return img, header.label


class ImageFolderDataset(Dataset):
    """label = subfolder index (reference ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        fname, label = self.items[idx]
        with open(fname, "rb") as f:
            buf = onp.frombuffer(f.read(), dtype=onp.uint8)
        img = nd.array(recordio._imdecode(buf, self._flag), dtype="uint8")
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
