"""Vision transforms (reference: python/mxnet/gluon/data/vision/transforms.py).

Transforms are HybridBlocks over the image ops (src/operator/image/ in the
reference), operating on HWC uint8/float images.
"""
from __future__ import annotations

import numpy as onp

from ... import nn
from ...block import Block, HybridBlock

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting", "RandomGray"]


class Compose(nn.Sequential):
    """Sequentially compose transforms."""

    def __init__(self, transforms):
        super().__init__()
        transforms.append(None)
        hybrid = []
        for i in transforms:
            if isinstance(i, HybridBlock):
                hybrid.append(i)
                continue
            if len(hybrid) == 1:
                self.add(hybrid[0])
            elif len(hybrid) > 1:
                hblock = nn.HybridSequential()
                for j in hybrid:
                    hblock.add(j)
                self.add(hblock)
            hybrid = []
            if i is not None:
                self.add(i)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        from .... import ndarray as nd_mod

        mean = onp.asarray(self._mean, dtype="float32").reshape(-1, 1, 1)
        std = onp.asarray(self._std, dtype="float32").reshape(-1, 1, 1)
        return (x - nd_mod.array(mean)) / nd_mod.array(std)


def _resize_hwc(x, size, interp=1):
    """Bilinear resize of an HWC image via jax.image."""
    import jax.image

    from .... import ndarray as nd_mod

    if isinstance(size, int):
        size = (size, size)
    w, h = size  # reference convention: (width, height)
    data = x._data.astype("float32")
    out = jax.image.resize(
        data, (h, w, data.shape[2]),
        method="nearest" if interp == 0 else "linear")
    return nd_mod.NDArray(out.astype(x._data.dtype))


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        if self._keep and isinstance(self._size, int):
            h, w = x.shape[0], x.shape[1]
            if h > w:
                size = (self._size, int(h * self._size / w))
            else:
                size = (int(w * self._size / h), self._size)
        else:
            size = self._size
        return _resize_hwc(x, size, self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        if isinstance(size, int):
            size = (size, size)
        self._size = size
        self._interpolation = interpolation

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        if H < h or W < w:
            x = _resize_hwc(x, (max(w, W), max(h, H)), self._interpolation)
            H, W = x.shape[0], x.shape[1]
        y0 = (H - h) // 2
        x0 = (W - w) // 2
        return x[y0 : y0 + h, x0 : x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        if isinstance(size, int):
            size = (size, size)
        self._size = size
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target_area = onp.random.uniform(*self._scale) * area
            log_ratio = (onp.log(self._ratio[0]), onp.log(self._ratio[1]))
            aspect = onp.exp(onp.random.uniform(*log_ratio))
            w = int(round(onp.sqrt(target_area * aspect)))
            h = int(round(onp.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = onp.random.randint(0, W - w + 1)
                y0 = onp.random.randint(0, H - h + 1)
                crop = x[y0 : y0 + h, x0 : x0 + w, :]
                return _resize_hwc(crop, self._size, self._interpolation)
        return CenterCrop(self._size, self._interpolation)(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[:, ::-1, :]
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[::-1, :, :]
        return x


class _RandomJitter(Block):
    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0):
        super().__init__()
        self._args = (brightness, contrast, saturation, hue)

    def forward(self, x):
        from .... import ndarray as nd_mod

        img = x.asnumpy().astype("float32")
        b, c, s, h = self._args
        if b > 0:
            img *= 1.0 + onp.random.uniform(-b, b)
        if c > 0:
            coef = onp.array([0.299, 0.587, 0.114], dtype="float32")
            alpha = 1.0 + onp.random.uniform(-c, c)
            gray_mean = (img * coef).sum() / (img.size / 3)
            img = img * alpha + gray_mean * (1 - alpha)
        if s > 0:
            coef = onp.array([0.299, 0.587, 0.114], dtype="float32")
            alpha = 1.0 + onp.random.uniform(-s, s)
            gray = (img * coef).sum(axis=2, keepdims=True)
            img = img * alpha + gray * (1 - alpha)
        if h > 0:
            alpha = onp.random.uniform(-h, h)
            u = onp.cos(alpha * onp.pi)
            w = onp.sin(alpha * onp.pi)
            bt = onp.array([[1.0, 0.0, 0.0],
                            [0.0, u, -w],
                            [0.0, w, u]], dtype="float32")
            t_yiq = onp.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], dtype="float32")
            t_rgb = onp.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], dtype="float32")
            t = t_rgb @ bt @ t_yiq
            img = img @ t.T
        return nd_mod.array(onp.clip(img, 0, 255), dtype="float32")


class RandomBrightness(_RandomJitter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)


class RandomContrast(_RandomJitter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)


class RandomSaturation(_RandomJitter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)


class RandomHue(_RandomJitter):
    def __init__(self, hue):
        super().__init__(hue=hue)


class RandomColorJitter(_RandomJitter):
    pass


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference random_lighting)."""

    _eigval = onp.array([55.46, 4.794, 1.148], dtype="float32")
    _eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], dtype="float32")

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        from .... import ndarray as nd_mod

        alpha = onp.random.normal(0, self._alpha, size=(3,)).astype(
            "float32")
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd_mod.array(
            x.asnumpy().astype("float32") + rgb, dtype="float32")


class RandomGray(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        from .... import ndarray as nd_mod

        if onp.random.rand() < self._p:
            coef = onp.array([0.299, 0.587, 0.114], dtype="float32")
            gray = (x.asnumpy().astype("float32") * coef).sum(
                axis=2, keepdims=True)
            return nd_mod.array(
                onp.broadcast_to(gray, x.shape).copy(), dtype="float32")
        return x
