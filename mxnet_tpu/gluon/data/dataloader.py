"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

Reference design: multi-process workers + shared-memory NDArray rebuild
via ForkingPickler (dataloader.py:28-92).  TPU-native redesign: workers
produce host numpy batches (pickled over pipes — no CUDA context issues
to dodge), and the main process device_puts once per batch; the
double-buffered host→HBM copy is the prefetch.
"""
from __future__ import annotations

import multiprocessing
import multiprocessing.pool
import sys

import numpy as onp

from ... import ndarray as nd
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference default_batchify_fn)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return nd.array(data, dtype=data.dtype)


def default_mp_batchify_fn(data):
    """Worker-side batchify: keep numpy (device_put happens in the main
    process — workers must not touch the accelerator)."""
    if isinstance(data[0], nd.NDArray):
        return onp.stack([d.asnumpy() for d in data])
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_mp_batchify_fn(i) for i in data]
    return onp.asarray(data)


def _numpy_to_nd(data):
    """device_put worker-produced numpy batches in the main process."""
    if isinstance(data, onp.ndarray):
        return nd.array(data, dtype=data.dtype)
    if isinstance(data, (list, tuple)):
        return [_numpy_to_nd(d) for d in data]
    return data


_worker_dataset = None


def _worker_initializer(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_fn(samples, batchify_fn, dataset=None):
    """Function for processing data in worker process."""
    global _worker_dataset
    ds = dataset if dataset is not None else _worker_dataset
    return batchify_fn([ds[i] for i in samples])


class _MultiWorkerIter:
    def __init__(self, worker_pool, batchify_fn, batch_sampler,
                 pin_memory=False, worker_fn=_worker_fn, prefetch=0,
                 dataset=None):
        self._worker_pool = worker_pool
        self._batchify_fn = batchify_fn
        self._batch_sampler = batch_sampler
        self._data_buffer = {}
        self._rcvd_idx = 0
        self._sent_idx = 0
        self._iter = iter(self._batch_sampler)
        self._worker_fn = worker_fn
        self._pin_memory = pin_memory
        self._dataset = dataset
        for _ in range(prefetch):
            self._push_next()

    def __len__(self):
        return len(self._batch_sampler)

    def _push_next(self):
        r = next(self._iter, None)
        if r is None:
            return
        async_ret = self._worker_pool.apply_async(
            self._worker_fn, (r, self._batchify_fn, self._dataset))
        self._data_buffer[self._sent_idx] = async_ret
        self._sent_idx += 1

    def __next__(self):
        self._push_next()
        if self._rcvd_idx == self._sent_idx:
            assert not self._data_buffer, (
                "Data buffer should be empty at this moment")
            raise StopIteration
        assert self._rcvd_idx < self._sent_idx, (
            "rcvd_idx must be smaller than sent_idx")
        assert self._rcvd_idx in self._data_buffer, (
            "fatal error with _push_next, rcvd_idx missing")
        ret = self._data_buffer.pop(self._rcvd_idx)
        batch = _numpy_to_nd(ret.get())
        self._rcvd_idx += 1
        return batch

    def next(self):
        return self.__next__()

    def __iter__(self):
        return self


class DataLoader:
    """Loads batches from a Dataset (reference gluon DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 prefetch=None, thread_pool=False, device_feed=None,
                 feed_depth=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        # async device feed (io.DeviceFeedIter): batches are already
        # device_put while the consumer's step runs.  None follows
        # MXNET_DEVICE_FEED (default on) — gluon training overlaps
        # host assembly + H2D with compute by default.
        self._device_feed = device_feed
        self._feed_depth = feed_depth
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._worker_pool = None
        self._prefetch = max(
            0, int(prefetch) if prefetch is not None
            else 2 * self._num_workers)
        if self._num_workers > 0:
            if self._thread_pool:
                self._worker_pool = multiprocessing.pool.ThreadPool(
                    self._num_workers)
            else:
                self._worker_pool = multiprocessing.get_context(
                    "fork").Pool(
                    self._num_workers,
                    initializer=_worker_initializer,
                    initargs=[self._dataset])
        if batchify_fn is None:
            if num_workers > 0 and not thread_pool:
                self._batchify_fn = default_mp_batchify_fn
            else:
                self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._num_workers == 0:

            def same_process_iter():
                for batch in self._batch_sampler:
                    ret = self._batchify_fn(
                        [self._dataset[idx] for idx in batch])
                    yield ret

            it = same_process_iter()
        else:
            it = _MultiWorkerIter(
                self._worker_pool, self._batchify_fn,
                self._batch_sampler,
                pin_memory=self._pin_memory, worker_fn=_worker_fn,
                prefetch=self._prefetch,
                # fork-Pool workers get the dataset via
                # _worker_initializer; ThreadPool workers share our
                # address space and need it passed
                dataset=self._dataset if self._thread_pool else None)
        from ...io.device_feed import DeviceFeedIter, device_feed_enabled

        feed = self._device_feed
        if feed is None:
            feed = device_feed_enabled()
        if feed:
            # fresh wrapper per epoch (the inner iterator is one-shot)
            return DeviceFeedIter(it, depth=self._feed_depth)
        return it

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        if self._worker_pool:
            assert isinstance(
                self._worker_pool,
                (multiprocessing.pool.Pool, multiprocessing.pool.ThreadPool))
            self._worker_pool.terminate()
