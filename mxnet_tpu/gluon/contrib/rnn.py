"""Contrib recurrent cells (reference
python/mxnet/gluon/contrib/rnn/{rnn_cell,conv_rnn_cell}.py):
VariationalDropoutCell and the Conv1D/2D/3D-RNN/LSTM/GRU family.
"""
from __future__ import annotations

from ..rnn.rnn_cell import (BidirectionalCell, HybridRecurrentCell,
                            ModifierCell, SequentialRNNCell)

__all__ = [
    "VariationalDropoutCell",
    "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
    "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
    "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
]


class VariationalDropoutCell(ModifierCell):
    """Variational (a.k.a. locked) dropout: ONE dropout mask per unroll
    for each of inputs/states/outputs, reused at every time step (Gal &
    Ghahramani; reference contrib/rnn/rnn_cell.py
    VariationalDropoutCell)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        # reference guards (contrib/rnn/rnn_cell.py:41): only a STATE
        # mask is ill-defined over a bidirectional base cell (the two
        # directions would share one locked h mask); input/output-only
        # dropout is well-defined and stays allowed
        assert not drop_states or \
            not isinstance(base_cell, BidirectionalCell), (
                "BidirectionalCell doesn't support variational "
                "state dropout; apply VariationalDropoutCell to the "
                "cells underneath instead.")
        assert not drop_states or \
            not (isinstance(base_cell, SequentialRNNCell)
                 and any(isinstance(c, BidirectionalCell)
                         for c in getattr(base_cell, "_children",
                                          {}).values())), (
                "Bidirectional SequentialRNNCell doesn't support "
                "variational state dropout; apply "
                "VariationalDropoutCell to the cells underneath "
                "instead.")
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    @staticmethod
    def _mask(F, p, like):
        # F-based like ZoneoutCell: keeps the modifier usable on the
        # symbolic/export path wherever its base cell is
        return F.Dropout(F.ones_like(like), p=p)

    def _base_not_steppable(self):
        base = self.base_cell
        return isinstance(base, BidirectionalCell) or (
            isinstance(base, SequentialRNNCell)
            and any(isinstance(c, BidirectionalCell)
                    for c in getattr(base, "_children", {}).values()))

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        # state dropout rides the recurrent loop (the locked h mask
        # applies inside every step) — that needs the step-wise base
        # unroll.  Input/output-only dropout is ONE mask broadcast
        # along the time axis, so for a base cell that cannot be
        # stepped (BidirectionalCell) it wraps the base cell's OWN
        # unroll instead — which is what makes io-only variational
        # dropout work over a BidirectionalCell again (reference
        # contrib/rnn/rnn_cell.py VariationalDropoutCell.unroll).
        if self.drop_states or not self._base_not_steppable():
            return super().unroll(length, inputs, begin_state, layout,
                                  merge_outputs,
                                  valid_length=valid_length)
        from ... import ndarray as nd
        from ..rnn.rnn_cell import (_format_sequence, _get_begin_state,
                                    _mask_sequence_variable_length)

        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs,
                                                    layout, True)
        states = _get_begin_state(self, nd, begin_state, inputs,
                                  batch_size)
        if self.drop_inputs:
            inputs = nd.Dropout(inputs, p=self.drop_inputs,
                                axes=(axis,))
        self.base_cell._modified = False
        try:
            outputs, states = self.base_cell.unroll(
                length, inputs, states, layout, merge_outputs=True,
                valid_length=valid_length)
        finally:
            self.base_cell._modified = True
        if self.drop_outputs:
            outputs = nd.Dropout(outputs, p=self.drop_outputs,
                                 axes=(axis,))
        merge_outputs = isinstance(outputs, nd.NDArray) if \
            merge_outputs is None else merge_outputs
        outputs, _, _ = _format_sequence(length, outputs, layout,
                                         merge_outputs)
        if valid_length is not None:
            outputs = _mask_sequence_variable_length(
                nd, outputs, length, valid_length, axis, merge_outputs)
        return outputs, states

    def hybrid_forward(self, F, inputs, states):
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(F, self.drop_inputs,
                                              inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(F, self.drop_states,
                                              states[0])
            # state dropout only applies to h (states[0]); the LSTM
            # cell state c must flow through unmasked (reference
            # contrib/rnn/rnn_cell.py hybrid_forward)
            states = list(states)
            states[0] = states[0] * self._state_mask
        output, next_states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(
                    F, self.drop_outputs, output)
            output = output * self._output_mask
        return output, next_states


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared machinery: i2h and h2h are Convolutions over the spatial
    dims, states are feature maps (reference conv_rnn_cell.py
    _BaseConvRNNCell)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, activation, num_gates,
                 prefix=None, params=None, conv_layout="NCHW"):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._hidden_channels = hidden_channels
        self._i2h_kernel = tuple(i2h_kernel)
        self._h2h_kernel = tuple(h2h_kernel)
        for k in self._h2h_kernel:
            assert k % 2 == 1, (
                "h2h kernel dims must be odd to preserve the state "
                f"shape, got {h2h_kernel}")
        self._i2h_pad = tuple(i2h_pad)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        self._activation = activation
        self._num_gates = num_gates
        in_c = self._input_shape[0]
        out_c = hidden_channels * num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(out_c, in_c) + self._i2h_kernel,
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(out_c, hidden_channels) + self._h2h_kernel,
                allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(out_c,), init="zeros",
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(out_c,), init="zeros",
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        # the state's spatial extent is the i2h conv's OUTPUT extent
        # (stride 1): (in + 2p - k) + 1 per dim — for non-same i2h_pad
        # (e.g. the valid-padding default of the reference) the state
        # shrinks accordingly; h2h (odd kernel, same-pad) preserves it
        spatial = tuple(
            s + 2 * p - k + 1
            for s, p, k in zip(self._input_shape[1:], self._i2h_pad,
                               self._i2h_kernel))
        shape = (batch_size, self._hidden_channels) + spatial
        n_state = 2 if self._num_gates == 4 else 1
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-len(
            spatial):]} for _ in range(n_state)]

    def _conv(self, F, x, weight, bias, kernel, pad):
        return F.Convolution(
            x, weight, bias, kernel=kernel,
            num_filter=self._hidden_channels * self._num_gates,
            pad=pad)

    def _gates(self, F, inputs, states, i2h_weight, h2h_weight,
               i2h_bias, h2h_bias):
        i2h = self._conv(F, inputs, i2h_weight, i2h_bias,
                         self._i2h_kernel, self._i2h_pad)
        h2h = self._conv(F, states[0], h2h_weight, h2h_bias,
                         self._h2h_kernel, self._h2h_pad)
        return i2h, h2h

    def _act(self, F, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, num_gates=1,
                         **kwargs)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight,
                       h2h_weight, i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states, i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        output = self._act(F, i2h + h2h)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, num_gates=4,
                         **kwargs)

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight,
                       h2h_weight, i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states, i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(slices[0], act_type="sigmoid")
        f = F.Activation(slices[1], act_type="sigmoid")
        g = self._act(F, slices[2])
        o = F.Activation(slices[3], act_type="sigmoid")
        next_c = f * states[1] + i * g
        next_h = o * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, num_gates=3,
                         **kwargs)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight,
                       h2h_weight, i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states, i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = F.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = F.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        cand = self._act(F, i2h_s[2] + reset * h2h_s[2])
        next_h = (1.0 - update) * cand + update * states[0]
        return next_h, [next_h]


def _specialize(base, ndim, name):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, i2h_pad=None, activation="tanh",
                 prefix=None, params=None):
        def tup(v):
            return (v,) * ndim if isinstance(v, int) else tuple(v)

        i2h_k = tup(i2h_kernel)
        h2h_k = tup(h2h_kernel)
        # reference default is VALID padding ((0,)*ndim —
        # conv_rnn_cell.py:265/332/399); same-padding is an explicit
        # opt-in via i2h_pad
        pad = tup(i2h_pad) if i2h_pad is not None else (0,) * ndim
        base.__init__(self, input_shape, hidden_channels, i2h_k, h2h_k,
                      pad, activation=activation, prefix=prefix,
                      params=params)

    return type(name, (base,), {"__init__": __init__,
                                "_spatial_ndim": ndim})


Conv1DRNNCell = _specialize(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _specialize(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _specialize(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _specialize(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _specialize(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _specialize(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _specialize(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _specialize(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _specialize(_ConvGRUCell, 3, "Conv3DGRUCell")
