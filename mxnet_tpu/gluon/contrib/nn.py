"""gluon.contrib.nn (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from ..nn.basic_layers import (BatchNorm, HybridSequential,
                               Sequential)

__all__ = ["SyncBatchNorm", "Identity", "Concurrent",
           "HybridConcurrent", "SparseEmbedding", "PixelShuffle1D",
           "PixelShuffle2D", "PixelShuffle3D"]


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference gluon/contrib/nn SyncBatchNorm
    over src/operator/contrib/sync_batch_norm.cc).

    Statistics reduce over ``axis_name`` when the forward runs inside a
    ``shard_map``/``pmap`` over that mesh axis (lax.pmean — the
    TPU-native AllReduce); outside a mapped context it behaves as
    BatchNorm on the full local batch, which matches the reference's
    single-device degenerate case.  ``num_devices`` is accepted for API
    compatibility.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", axis_name=None,
                 **kwargs):
        super().__init__(
            axis=1, momentum=momentum, epsilon=epsilon, center=center,
            scale=scale, use_global_stats=use_global_stats,
            beta_initializer=beta_initializer,
            gamma_initializer=gamma_initializer,
            running_mean_initializer=running_mean_initializer,
            running_variance_initializer=running_variance_initializer,
            in_channels=in_channels, **kwargs)
        if num_devices is not None and num_devices < 1:
            raise MXNetError("num_devices must be >= 1")
        self._kwargs["axis_name"] = axis_name
        del self._kwargs["axis"]  # SyncBatchNorm op is channel-1 only

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd

        training = (autograd.is_training()
                    and not self._kwargs["use_global_stats"])
        if training:
            out, batch_mean, batch_var = F.SyncBatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **self._kwargs)
            m = self._kwargs["momentum"]
            with autograd.pause():
                new_mean = m * running_mean + (1.0 - m) * batch_mean
                new_var = m * running_var + (1.0 - m) * batch_var
                running_mean._adopt(new_mean._data)
                running_var._adopt(new_var._data)
            return out
        return F.SyncBatchNorm(x, gamma, beta, running_mean, running_var,
                               **self._kwargs)


class Identity(HybridBlock):
    """Pass-through block (reference basic_layers.py Identity) — the
    no-op branch for Concurrent/HybridConcurrent compositions."""

    def hybrid_forward(self, F, x):
        return x


class Concurrent(Sequential):
    """Run children on the SAME input and concatenate their outputs
    along ``axis`` (reference basic_layers.py Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd

        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (reference HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        outs = [child(x) for child in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class SparseEmbedding(HybridBlock):
    """Embedding with the reference's row_sparse gradient surface
    (contrib SparseEmbedding).  Storage is dense-backed on TPU (README
    scope decision) but the call signature and semantics match."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim,
                        "output_dim": output_dim}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return (f"SparseEmbedding({self._kwargs['input_dim']} -> "
                f"{self._kwargs['output_dim']})")


class _PixelShuffle(HybridBlock):
    """Rearrange channel blocks into spatial upscaling (reference
    basic_layers.py PixelShuffle1D/2D/3D).  Implemented entirely with
    F reshape/transpose (the reference's -4/-3 split-merge codes), so
    it traces on BOTH the eager and the symbolic/export paths."""

    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        self._factors = ((int(factor),) * ndim
                         if isinstance(factor, int)
                         else tuple(int(f) for f in factor))
        assert len(self._factors) == ndim


class PixelShuffle1D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def hybrid_forward(self, F, x):
        (f,) = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f, 0))   # (N, C, f, W)
        x = F.transpose(x, axes=(0, 1, 3, 2))       # (N, C, W, f)
        return F.reshape(x, shape=(0, 0, -3))       # (N, C, W*f)


class PixelShuffle2D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        return F.reshape(x, shape=(0, 0, -3, -3))


class PixelShuffle3D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, 0, -4, f2, f3, 0, 0, 0))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(0, 0, -3, -3, -3))
