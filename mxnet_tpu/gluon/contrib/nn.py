"""gluon.contrib.nn (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py)."""
from __future__ import annotations

from ...base import MXNetError
from ..nn.basic_layers import BatchNorm

__all__ = ["SyncBatchNorm"]


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference gluon/contrib/nn SyncBatchNorm
    over src/operator/contrib/sync_batch_norm.cc).

    Statistics reduce over ``axis_name`` when the forward runs inside a
    ``shard_map``/``pmap`` over that mesh axis (lax.pmean — the
    TPU-native AllReduce); outside a mapped context it behaves as
    BatchNorm on the full local batch, which matches the reference's
    single-device degenerate case.  ``num_devices`` is accepted for API
    compatibility.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", axis_name=None,
                 **kwargs):
        super().__init__(
            axis=1, momentum=momentum, epsilon=epsilon, center=center,
            scale=scale, use_global_stats=use_global_stats,
            beta_initializer=beta_initializer,
            gamma_initializer=gamma_initializer,
            running_mean_initializer=running_mean_initializer,
            running_variance_initializer=running_variance_initializer,
            in_channels=in_channels, **kwargs)
        if num_devices is not None and num_devices < 1:
            raise MXNetError("num_devices must be >= 1")
        self._kwargs["axis_name"] = axis_name
        del self._kwargs["axis"]  # SyncBatchNorm op is channel-1 only

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd

        training = (autograd.is_training()
                    and not self._kwargs["use_global_stats"])
        if training:
            out, batch_mean, batch_var = F.SyncBatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **self._kwargs)
            m = self._kwargs["momentum"]
            with autograd.pause():
                new_mean = m * running_mean + (1.0 - m) * batch_mean
                new_var = m * running_var + (1.0 - m) * batch_var
                running_mean._adopt(new_mean._data)
                running_var._adopt(new_var._data)
            return out
        return F.SyncBatchNorm(x, gamma, beta, running_mean, running_var,
                               **self._kwargs)
