"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""
from . import data, estimator, nn, rnn  # noqa: F401
