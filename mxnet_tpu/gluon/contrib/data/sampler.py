"""Contrib samplers (reference gluon/contrib/data/sampler.py)."""
from __future__ import annotations

from ...data.sampler import Sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(Sampler):
    """Sample at fixed intervals with rollover (reference
    IntervalSampler: for length=N, interval=k yields
    0, k, 2k, ..., 1, k+1, ... covering every index once)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length, (
            f"interval {interval} must not be larger than length "
            f"{length}")
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        starts = range(self._interval) if self._rollover else [0]
        for start in starts:
            yield from range(start, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))
