"""Contrib data helpers (reference python/mxnet/gluon/contrib/data/)."""
from . import sampler  # noqa: F401
from .sampler import IntervalSampler  # noqa: F401

__all__ = ["sampler", "IntervalSampler"]
