"""Estimator — the gluon training-loop driver.

Reference parity: gluon/contrib/estimator/estimator.py:40 (Estimator)
and :283 (fit loop dispatching event handlers)."""
from __future__ import annotations

from .... import autograd, metric as metric_mod
from ....base import MXNetError
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, StoppingHandler, TrainBegin,
                            TrainEnd)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None, val_metrics=None):
        self.net = net
        self.loss = loss
        self.train_metrics = self._norm_metrics(train_metrics)
        self.val_metrics = self.__init_val_metrics(val_metrics)
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.001})

    @staticmethod
    def _norm_metrics(metrics):
        if metrics is None:
            return [metric_mod.Accuracy()]
        if isinstance(metrics, metric_mod.EvalMetric):
            return [metrics]
        return list(metrics)

    def __init_val_metrics(self, val_metrics):
        if val_metrics is not None:
            return self._norm_metrics(val_metrics)
        # independent copies: evaluate() must not reset/overwrite the
        # train metrics mid-fit
        import copy

        return [copy.deepcopy(m) for m in self.train_metrics]

    def _dispatch(self, handlers, event, *args, **kwargs):
        for h in handlers:
            fn = getattr(h, event, None)
            if fn is not None:
                fn(self, *args, **kwargs)

    def evaluate(self, val_data):
        for m in self.val_metrics:
            m.reset()
        for batch in val_data:
            data, label = self._unpack(batch)
            out = self.net(data)
            for m in self.val_metrics:
                m.update([label], [out])
        return {m.get()[0]: m.get()[1] for m in self.val_metrics}

    @staticmethod
    def _unpack(batch):
        if isinstance(batch, (list, tuple)) and len(batch) == 2:
            return batch[0], batch[1]
        if hasattr(batch, "data"):
            return batch.data[0], batch.label[0]
        raise MXNetError("cannot unpack batch")

    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None):
        if epochs is None and batches is None:
            raise MXNetError(
                "fit() needs a stopping condition: pass epochs and/or "
                "batches (reference estimator raises the same)")
        handlers = list(event_handlers or [])
        stopper = StoppingHandler(max_epoch=epochs, max_batch=batches)
        handlers.append(stopper)
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler())
        begin = [h for h in handlers if isinstance(h, TrainBegin)]
        end = [h for h in handlers if isinstance(h, TrainEnd)]
        e_begin = [h for h in handlers if isinstance(h, EpochBegin)]
        e_end = [h for h in handlers if isinstance(h, EpochEnd)]
        b_begin = [h for h in handlers if isinstance(h, BatchBegin)]
        b_end = [h for h in handlers if isinstance(h, BatchEnd)]

        self._dispatch(begin, "train_begin")
        stop = (epochs == 0 or batches == 0)
        while not stop:
            self._dispatch(e_begin, "epoch_begin")
            for m in self.train_metrics:
                m.reset()
            for batch in train_data:
                self._dispatch(b_begin, "batch_begin", batch=batch)
                data, label = self._unpack(batch)
                with autograd.record():
                    out = self.net(data)
                    loss = self.loss(out, label)
                loss.backward()
                self.trainer.step(data.shape[0])
                for m in self.train_metrics:
                    m.update([label], [out])
                self._dispatch(b_end, "batch_end", batch=batch)
                stop = any(getattr(h, "stop_training", False)
                           for h in handlers)
                if stop:
                    break
            if val_data is not None:
                self.evaluate(val_data)
            self._dispatch(e_end, "epoch_end")
            stop = stop or any(getattr(h, "stop_training", False)
                               for h in handlers)
        self._dispatch(end, "train_end")
