"""Estimator event handlers (reference:
gluon/contrib/estimator/event_handler.py)."""
from __future__ import annotations

import logging
import os

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch/max_batch (reference event_handler.py:94)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch is not None and \
                self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch is not None and \
                self.current_epoch >= self.max_epoch:
            self.stop_training = True


class LoggingHandler(TrainBegin, TrainEnd, EpochEnd, BatchEnd):
    """Periodic metric logging (reference event_handler.py:154)."""

    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self._batches = 0

    def train_begin(self, estimator, *args, **kwargs):
        self._batches = 0  # reusable across fit() calls
        logging.info("training begin")

    def train_end(self, estimator, *args, **kwargs):
        logging.info("training end: %s", self._fmt(estimator))

    def _fmt(self, estimator):
        return " ".join(f"{m.get()[0]}={m.get()[1]:.4f}"
                        for m in (self.metrics
                                  or estimator.train_metrics))

    def batch_end(self, estimator, *args, **kwargs):
        self._batches += 1
        if isinstance(self.log_interval, int) and \
                self._batches % self.log_interval == 0:
            logging.info("batch %d: %s", self._batches,
                         self._fmt(estimator))

    def epoch_end(self, estimator, *args, **kwargs):
        logging.info("epoch end: %s", self._fmt(estimator))


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save parameters every epoch (reference event_handler.py:349)."""

    def __init__(self, model_dir, model_prefix="model", save_best=False,
                 monitor=None, mode="min"):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.save_best = save_best
        self.monitor = monitor
        self.mode = mode
        self._best = None
        self._epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        self._epoch = 0  # reusable across fit() calls
        self._best = None

    def epoch_end(self, estimator, *args, **kwargs):
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-epoch{self._epoch}"
                            ".params")
        estimator.net.save_parameters(path)
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            better = (self._best is None
                      or (val < self._best if self.mode == "min"
                          else val > self._best))
            if better:
                self._best = val
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))
        self._epoch += 1


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stop when a metric stops improving (reference
    event_handler.py:533)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="min"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self._best = None
        self._waited = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self._best = None
        self._waited = 0
        self.stop_training = False

    def epoch_end(self, estimator, *args, **kwargs):
        _, val = self.monitor.get()
        improved = (self._best is None
                    or (val < self._best - self.min_delta
                        if self.mode == "min"
                        else val > self._best + self.min_delta))
        if improved:
            self._best = val
            self._waited = 0
        else:
            self._waited += 1
            if self._waited > self.patience:
                self.stop_training = True
