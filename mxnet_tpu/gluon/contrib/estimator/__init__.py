"""Gluon Estimator (reference: python/mxnet/gluon/contrib/estimator/)."""
from .estimator import Estimator  # noqa: F401
from .event_handler import (BatchBegin, BatchEnd, CheckpointHandler,  # noqa: F401
                            EarlyStoppingHandler, EpochBegin, EpochEnd,
                            LoggingHandler, StoppingHandler, TrainBegin,
                            TrainEnd)
