"""Gluon — the imperative/hybrid neural network API.

Reference parity: python/mxnet/gluon/ (Block/HybridBlock, Parameter,
Trainer, nn/rnn layers, losses, data, model_zoo).
"""
from . import block  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .parameter import (  # noqa: F401
    Constant,
    DeferredInitializationError,
    Parameter,
    ParameterDict,
)
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import contrib  # noqa: F401

import importlib as _importlib

_LAZY = {
    "rnn": ".rnn",
    "data": ".data",
    "trainer": ".trainer",
    "Trainer": (".trainer", "Trainer"),
    "model_zoo": ".model_zoo",
    "contrib": ".contrib",
    "utils": ".utils",
}


def __getattr__(name):
    if name in _LAZY:
        spec = _LAZY[name]
        if isinstance(spec, tuple):
            mod = _importlib.import_module(spec[0], __name__)
            obj = getattr(mod, spec[1])
        else:
            obj = _importlib.import_module(spec, __name__)
        globals()[name] = obj
        return obj
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")
