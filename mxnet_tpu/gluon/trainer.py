"""Trainer — applies an Optimizer to a set of Parameters.

Reference parity: python/mxnet/gluon/trainer.py (``Trainer`` :27,
``_init_kvstore`` :169 deciding update_on_kvstore, ``allreduce_grads``
:334, ``step`` :305, ``update`` :366).

TPU-native redesign: parameters have ONE logical copy, so the reference's
multi-device allreduce collapses to a no-op on one chip; under a device
mesh, gradients arriving from a pjit/shard_map step are already psum-ed by
XLA collectives.  ``update_on_kvstore`` therefore only matters for the
dist parameter-server emulation path; the fast path applies jitted update
rules directly.
"""
from __future__ import annotations

from .. import kvstore as kvs
from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise MXNetError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore,
            "update_on_kvstore": update_on_kvstore,
        }
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(
                optimizer, param_dict=param_dict, **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if kvstore and isinstance(kvstore, str) and kvstore.startswith(
            "dist"
        ):
            self._kvstore = kvs.create(kvstore)
            if update_on_kvstore is None:
                update_on_kvstore = True
            if update_on_kvstore:
                # share the LOCAL updater instance with the store so
                # optimizer state lives in exactly one place
                # (save_states/load_states stay consistent)
                self._kvstore._set_updater(self._updaters[0])
        elif isinstance(kvstore, kvs.KVStore):
            self._kvstore = kvstore
            if update_on_kvstore:
                self._kvstore._set_updater(self._updaters[0])
        else:
            # single-process local/device: one logical copy — no kvstore
            self._kvstore = None
            update_on_kvstore = False
        if self._kvstore is not None and self._compression_params:
            self._kvstore.set_gradient_compression(
                self._compression_params)
        self._update_on_kvstore = bool(update_on_kvstore)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _kv_lazy_init(self, i, value):
        if i not in self._kvstore._store:
            self._kvstore.init(i, value)

    def allreduce_grads(self):
        """Sum gradients across workers (reference trainer.py:334).

        With a dist kvstore and update_on_kvstore=False, gradients are
        pushed/pulled through the store — each worker ends up holding
        the GLOBAL gradient sum before the local update (the reference's
        kvstore.pushpull path).  Single-process: identity."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None or self._update_on_kvstore:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            g = param._data._grad
            if g is None:
                continue
            # push EVERY allocated grad, fresh or stale (zeros for
            # stale): the push sequence must be identical on every
            # worker or the collectives deadlock/mismatch
            self._kv_lazy_init(i, nd.zeros(g.shape, dtype=g.dtype))
            if param._data._fresh_grad:
                self._kvstore.push(i, g)
            else:
                self._kvstore.push(i, nd.zeros(g.shape, dtype=g.dtype))
            self._kvstore.pull(i, out=g)

    def step(self, batch_size, ignore_stale_grad=False):
        """rescale + allreduce + update (reference trainer.py:305)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            # AMP dynamic loss scaling: skip the whole update on overflow
            # (reference contrib/amp trainer integration + all_finite op)
            overflow = scaler.has_overflow(self._params)
            if self._kvstore is not None and \
                    self._kvstore.num_workers > 1:
                # the skip decision must be GLOBAL, or workers issue
                # mismatched collectives below and deadlock
                flag = nd.array([1.0 if overflow else 0.0])
                total = self._kvstore._allreduce(flag._data)
                overflow = float(total[0]) > 0
            scaler.update_scale(overflow)
            if overflow:
                for param in self._params:
                    if param._data is not None:
                        param._data._fresh_grad = False
                return
        updater = self._updaters[0]
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._data is None:
                if param._deferred_init is not None and ignore_stale_grad:
                    continue
                raise MXNetError(
                    f"Parameter {param.name} has not been initialized")
            stale = (param._data._grad is None
                     or not param._data._fresh_grad)
            if stale and not ignore_stale_grad:
                raise MXNetError(
                    f"Gradient of Parameter `{param.name}` on context "
                    "has not been updated by backward since last `step`. "
                    "This could mean a bug in your model that made it only "
                    "use a subset of the Parameters for the last forward "
                    "pass. Set ignore_stale_grad=True to suppress this "
                    "warning.")
            if self._update_on_kvstore and self._kvstore is not None:
                # "server-side" update: push grad (allreduced across
                # workers), shared updater mutates the stored weight,
                # pull the new weight back (model.py:150 analog).
                # Stale grads push zeros — the collective sequence must
                # match on every worker.
                self._kv_lazy_init(i, param._data)
                g = param._data._grad if not stale else nd.zeros(
                    param._data.shape, dtype=param._data.dtype)
                self._kvstore.push(i, g)
                self._kvstore.pull(i, out=param._data)
            elif stale:
                continue
            else:
                updater(i, param._data._grad, param._data)
            param._data._fresh_grad = False

    def save_states(self, fname):
        """Save optimizer (updater) states.

        _update() always applies updates through the local updater —
        even under dist kvstores, where gradient reduction is XLA's job
        and the 'server-side optimizer' of the reference has no separate
        state — so states are always saved from/loaded into
        self._updaters[0] regardless of _update_on_kvstore.
        """
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "wb") as fout:
            fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        for updater in self._updaters:
            updater.set_states(states)
            updater.optimizer = self._updaters[0].optimizer
        self._optimizer = self._updaters[0].optimizer
        if self._update_on_kvstore and self._kvstore is not None:
            # keep the ONE shared updater instance (set_optimizer would
            # install a fresh empty-state updater and fork the state)
            self._kvstore._set_updater(self._updaters[0])
