"""LeNet-5 — the reference's train_mnist.py model
(example/image-classification/symbols/lenet.py), here as a HybridBlock.
The minimum end-to-end slice model (SURVEY.md §7 stage 4)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["LeNet", "lenet"]


class LeNet(HybridBlock):
    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(20, kernel_size=5,
                                        activation="tanh"))
            self.features.add(nn.MaxPool2D(pool_size=2, strides=2))
            self.features.add(nn.Conv2D(50, kernel_size=5,
                                        activation="tanh"))
            self.features.add(nn.MaxPool2D(pool_size=2, strides=2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(500, activation="tanh"))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def lenet(**kwargs):
    kwargs.pop("pretrained", None)
    return LeNet(**kwargs)
