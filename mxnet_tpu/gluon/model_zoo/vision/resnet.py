"""ResNet v1/v2 (reference: python/mxnet/gluon/model_zoo/vision/resnet.py).

The BASELINE flagship model (SURVEY.md §6: ResNet-50 img/s is the headline
benchmark).  Structure matches the reference exactly (BasicBlockV1/
BottleneckV1/BasicBlockV2/BottleneckV2, 18/34/50/101/152 layer configs) so
parameter counts line up.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = [
    "ResNetV1", "ResNetV2",
    "BasicBlockV1", "BasicBlockV2", "BottleneckV1", "BottleneckV2",
    "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
    "resnet152_v1",
    "resnet18_v2", "resnet34_v2", "resnet50_v2", "resnet101_v2",
    "resnet152_v2",
    "get_resnet",
]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


def _is_symbolic(F):
    """True when hybrid_forward is tracing the symbolic graph (export
    path) — the fused-kernel shortcut keeps the canonical layer graph
    there so exported JSON matches the reference topology."""
    return not hasattr(F, "NDArray")


class BasicBlockV1(HybridBlock):
    # no_bias is accepted for API uniformity with BottleneckV1: every
    # conv in this block is already bias-free, so True is a no-op that
    # still yields the bias-free model the caller asked for.
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 no_bias=False, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    # The reference gluon zoo leaves biases ON the two 1x1 body convs
    # (python/mxnet/gluon/model_zoo/vision/resnet.py BottleneckV1) even
    # though each is immediately followed by BatchNorm, which makes the
    # bias mathematically inert (its gradient is exactly zero).  The
    # reference's own benchmark symbol sets no_bias=True everywhere
    # (example/image-classification/symbols/resnet.py); ``no_bias=True``
    # reproduces that (and skips the dead bias traffic on TPU).  Default
    # keeps the zoo quirk so `.params` checkpoints stay exchangeable.
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 no_bias=False, **kwargs):
        super().__init__(**kwargs)
        use_bias = not no_bias
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1,
                                strides=stride, use_bias=use_bias))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                use_bias=use_bias))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None
        # fused bn2->relu->conv3 tail (ops/pallas_conv.py): eligible when
        # the net is channel-last and conv3 is bias-free — the expansion
        # conv's activation is private to it, so the one-pass Pallas
        # backward can absorb the relu mask + BN reductions.  The body
        # structure is verified here so a future reshuffle DISABLES the
        # fusion instead of silently fusing the wrong layers.
        self._fusable_tail = (not use_bias
                              and nn.layout.is_channel_last()
                              and self._tail_structure_ok())

    def _tail_structure_ok(self):
        body = list(self.body._children.values())
        if len(body) != 8:
            return False
        bn2, act2, conv3 = body[4], body[5], body[6]
        return (isinstance(bn2, nn.BatchNorm)
                and isinstance(conv3, nn.Conv2D)
                and isinstance(body[7], nn.BatchNorm)
                and getattr(act2, "_act_type", None) == "relu"
                and getattr(conv3, "_kwargs", {}).get("kernel")
                == (1, 1)
                and getattr(conv3, "_kwargs", {}).get("stride",
                                                      (1, 1)) == (1, 1))

    def _fused_tail(self, F, t):
        """bn2 -> relu -> conv3 through the fused kernel; replicates the
        BatchNorm layer's running-stat update."""
        from .... import autograd
        from ....ops import pallas_conv

        body = list(self.body._children.values())
        bn2, conv3 = body[4], body[6]
        if not (pallas_conv.enabled() and autograd.is_training()
                and not bn2._kwargs["use_global_stats"]):
            return None
        try:
            gamma, beta = bn2.gamma.data(), bn2.beta.data()
            rmean, rvar = bn2.running_mean.data(), bn2.running_var.data()
            weight = conv3.weight.data()
        except Exception:  # deferred shapes: first eager pass runs plain
            return None
        y, bmean, bvar = F._contrib_BNReluConv(
            t, gamma, beta, weight, eps=bn2._kwargs["eps"],
            fix_gamma=bn2._kwargs["fix_gamma"])
        m = bn2._kwargs["momentum"]
        with autograd.pause():
            rmean._adopt((m * rmean + (1.0 - m) * bmean)._data)
            rvar._adopt((m * rvar + (1.0 - m) * bvar)._data)
        return y

    def hybrid_forward(self, F, x):
        residual = x
        if self._fusable_tail and not _is_symbolic(F):
            body = list(self.body._children.values())
            t = x
            for layer in body[:4]:   # conv1, bn1, relu, conv2(3x3)
                t = layer(t)
            y = self._fused_tail(F, t)
            if y is not None:
                x = body[7](y)       # bn3
            else:                    # ineligible call: plain tail
                x = t
                for layer in body[4:]:
                    x = layer(x)
        else:
            x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    # no_bias is accepted for API uniformity with BottleneckV1: every
    # conv in this block is already bias-free, so True is a no-op that
    # still yields the bias-free model the caller asked for.
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 no_bias=False, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(
                channels, 1, stride, use_bias=False,
                in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    # no_bias is accepted for API uniformity with BottleneckV1: every
    # conv in this block is already bias-free, so True is a no-op that
    # still yields the bias-free model the caller asked for.
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 no_bias=False, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(
                channels, 1, stride, use_bias=False,
                in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, no_bias=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._no_bias = no_bias
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(
                    channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        extra = {"no_bias": True} if self._no_bias else {}
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix="", **extra))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix="", **extra))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, no_bias=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._no_bias = no_bias
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(
                    channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}

resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               layout=None, **kwargs):
    """``layout="NHWC"`` builds the net channel-last (TPU-native: convs
    feed the MXU without layout transposes); inputs must then be NHWC.
    Default follows the ambient ``nn.default_layout`` scope (NCHW)."""
    if num_layers not in resnet_spec:
        raise MXNetError(
            f"Invalid number of layers: {num_layers}. "
            f"Options are {sorted(resnet_spec.keys())}")
    if version not in (1, 2):
        raise MXNetError(f"Invalid resnet version: {version} (1 or 2)")
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    with nn.default_layout(layout):
        net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise MXNetError(
            "pretrained weights are not downloadable in this environment; "
            "use net.load_parameters(<local .params>) instead")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
