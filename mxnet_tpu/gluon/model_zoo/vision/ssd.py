"""SSD object detector (reference: example/ssd — VGG16-reduced SSD via
the multibox contrib ops; see also symbol/symbol_builder.py there).

TPU-native design: one HybridBlock emitting (cls_preds, loc_preds,
anchors) with static shapes; training targets come from MultiBoxTarget,
inference from MultiBoxDetection — the same contrib ops the reference
symbol graph uses (src/operator/contrib/multibox_*.cc), so the training
recipe carries over unchanged.
"""
from __future__ import annotations

from .... import ndarray as nd
from ...block import HybridBlock
from ...nn import Conv2D, HybridSequential, MaxPool2D

__all__ = ["SSD", "get_ssd", "ssd_300_vgg16_reduced", "ssd_512_vgg16",
           "ssd_300_resnet18"]


def _vgg_reduced_features():
    """VGG16-reduced backbone stages (reference example/ssd
    symbol/vgg16_reduced.py), returning blocks whose outputs feed the
    multi-scale heads."""
    stage1 = HybridSequential()
    for channels, n in [(64, 2), (128, 2), (256, 3)]:
        for _ in range(n):
            stage1.add(Conv2D(channels, 3, padding=1, activation="relu"))
        stage1.add(MaxPool2D(2, 2))
    for _ in range(3):
        stage1.add(Conv2D(512, 3, padding=1, activation="relu"))
    # stage1 output: conv4_3 (first anchor scale)
    stage2 = HybridSequential()
    stage2.add(MaxPool2D(2, 2))
    for _ in range(3):
        stage2.add(Conv2D(512, 3, padding=1, activation="relu"))
    stage2.add(MaxPool2D(3, 1, padding=1))
    stage2.add(Conv2D(1024, 3, padding=6, dilation=6,
                      activation="relu"))  # fc6 atrous
    stage2.add(Conv2D(1024, 1, activation="relu"))  # fc7
    return [stage1, stage2]


def _resnet18_features():
    from .resnet import get_resnet

    net = get_resnet(1, 18, classes=10)
    feats = net.features
    children = list(feats._children.values())
    # features = [Conv, BN, ReLU, MaxPool, stage1..4, GlobalAvgPool]
    stage1 = HybridSequential()
    for c in children[:-2]:  # through stage 3 (stride 16)
        stage1.add(c)
    stage2 = HybridSequential()
    stage2.add(children[-2])  # stage 4 (stride 32)
    return [stage1, stage2]


class SSD(HybridBlock):
    """Single-shot detector head over a multi-stage backbone.

    forward(x) -> (cls_preds (B, N, classes+1), loc_preds (B, N*4),
    anchors (1, N, 4)).
    """

    def __init__(self, backbone_stages, num_classes, sizes, ratios,
                 extra_channels=(512, 256, 256, 256), prefix=None,
                 params=None, **kwargs):
        super().__init__(prefix=prefix, params=params, **kwargs)
        self.num_classes = num_classes  # foreground classes
        self._sizes = sizes
        self._ratios = ratios
        with self.name_scope():
            self.stages = HybridSequential()
            for s in backbone_stages:
                self.stages.add(s)
            # extra downsampling feature blocks (reference ssd extra
            # layers: 1x1 squeeze + 3x3 stride-2)
            self.extras = HybridSequential()
            n_extra = len(sizes) - len(backbone_stages)
            for i in range(n_extra):
                blk = HybridSequential()
                ch = extra_channels[min(i, len(extra_channels) - 1)]
                blk.add(Conv2D(ch // 2, 1, activation="relu"))
                blk.add(Conv2D(ch, 3, strides=2, padding=1,
                               activation="relu"))
                self.extras.add(blk)
            self.class_preds = HybridSequential()
            self.loc_preds = HybridSequential()
            for i in range(len(sizes)):
                a = len(sizes[i]) + len(ratios[i]) - 1
                self.class_preds.add(
                    Conv2D(a * (num_classes + 1), 3, padding=1))
                self.loc_preds.add(Conv2D(a * 4, 3, padding=1))

    def hybrid_forward(self, F, x):
        feats = []
        for stage in self.stages._children.values():
            x = stage(x)
            feats.append(x)
        for blk in self.extras._children.values():
            x = blk(x)
            feats.append(x)
        cls_out, loc_out, anchor_out = [], [], []
        cps = list(self.class_preds._children.values())
        lps = list(self.loc_preds._children.values())
        for i, feat in enumerate(feats):
            cp = cps[i](feat)  # (B, A*(C+1), h, w)
            lp = lps[i](feat)  # (B, A*4, h, w)
            anchors = nd.invoke("_contrib_MultiBoxPrior", [feat],
                                sizes=tuple(self._sizes[i]),
                                ratios=tuple(self._ratios[i]),
                                clip=False)
            b = cp.shape[0]
            cp = cp.transpose(axes=(0, 2, 3, 1)).reshape(
                (b, -1, self.num_classes + 1))
            lp = lp.transpose(axes=(0, 2, 3, 1)).reshape((b, -1))
            cls_out.append(cp)
            loc_out.append(lp)
            anchor_out.append(anchors)
        cls_preds = nd.concat(*cls_out, dim=1) if len(cls_out) > 1 \
            else cls_out[0]
        loc_preds = nd.concat(*loc_out, dim=1) if len(loc_out) > 1 \
            else loc_out[0]
        anchors = nd.concat(*anchor_out, dim=1) if len(anchor_out) > 1 \
            else anchor_out[0]
        return cls_preds, loc_preds, anchors

    # ------------------------------------------------- train / inference
    def training_targets(self, anchors, class_preds, labels,
                         overlap_threshold=0.5,
                         negative_mining_ratio=3.0):
        """MultiBoxTarget wrapper (reference training_targets in
        example/ssd/symbol/symbol_builder.py)."""
        cls_pred_t = class_preds.transpose(axes=(0, 2, 1))
        return nd.invoke(
            "_contrib_MultiBoxTarget", [anchors, labels, cls_pred_t],
            overlap_threshold=overlap_threshold,
            negative_mining_ratio=negative_mining_ratio,
            negative_mining_thresh=0.5)

    def detect(self, cls_preds, loc_preds, anchors, nms_threshold=0.45,
               threshold=0.01, nms_topk=400):
        cls_prob = nd.softmax(cls_preds, axis=-1).transpose(
            axes=(0, 2, 1))
        return nd.invoke(
            "_contrib_MultiBoxDetection", [cls_prob, loc_preds, anchors],
            nms_threshold=nms_threshold, threshold=threshold,
            nms_topk=nms_topk)


def get_ssd(backbone="vgg16_reduced", num_classes=20, sizes=None,
            ratios=None, **kwargs):
    if sizes is None:
        sizes = [[0.1, 0.141], [0.2, 0.272], [0.37, 0.447],
                 [0.54, 0.619], [0.71, 0.79], [0.88, 0.961]]
    if ratios is None:
        ratios = [[1, 2, 0.5]] * 2 + [[1, 2, 0.5, 3, 1.0 / 3]] * 3 + \
            [[1, 2, 0.5]]
        ratios = ratios[: len(sizes)]
    if backbone == "vgg16_reduced":
        stages = _vgg_reduced_features()
    elif backbone == "resnet18":
        stages = _resnet18_features()
    else:
        raise ValueError(f"unknown ssd backbone {backbone}")
    return SSD(stages, num_classes, sizes, ratios, **kwargs)


def ssd_300_vgg16_reduced(num_classes=20, **kwargs):
    """SSD-300 with the VGG16-reduced backbone (the BASELINE SSD
    workload, example/ssd/train.py defaults)."""
    return get_ssd("vgg16_reduced", num_classes, **kwargs)


def ssd_512_vgg16(num_classes=20, **kwargs):
    """SSD-512: 7 anchor scales (reference example/ssd symbol_factory
    512-input configuration)."""
    sizes = [[0.07, 0.1025], [0.15, 0.2121], [0.3, 0.3674],
             [0.45, 0.4950], [0.6, 0.6315], [0.75, 0.7721],
             [0.9, 0.9557]]
    ratios = [[1, 2, 0.5]] * 2 + [[1, 2, 0.5, 3, 1.0 / 3]] * 3 + \
        [[1, 2, 0.5]] * 2
    return get_ssd("vgg16_reduced", num_classes, sizes=sizes,
                   ratios=ratios, **kwargs)


def ssd_300_resnet18(num_classes=20, **kwargs):
    sizes = [[0.1, 0.141], [0.2, 0.272], [0.37, 0.447], [0.54, 0.619]]
    ratios = [[1, 2, 0.5]] * 4
    return get_ssd("resnet18", num_classes, sizes=sizes, ratios=ratios,
                   **kwargs)
