"""Elastic training runtime (SURVEY.md §5.3 "a floor, not a ceiling").

The resilience layer the reference never had, on top of the
shard-restart recovery already proven in ``mxnet_tpu._ps``:

* :mod:`.checkpoint` — atomic, versioned, CRC-verified checkpoints
  with a ``latest`` pointer and previous-good fallback.
* :mod:`.preempt` — SIGTERM/SIGINT drain-to-checkpoint for
  ``Module.fit``.
* :mod:`.faultsim` — deterministic, hit-count-armed fault injection
  (``MXNET_FAULT_SPEC``).
* :mod:`.retry` — the shared bounded exponential-backoff-with-jitter
  helper (device-feed producer, PS client ops).
* :mod:`.elastic` — multi-host bring-up (``jax.distributed`` with a
  bounded-retry barrier), topology-stamped checkpoints and the
  reshard-on-resize verdict: losing k hosts is a reshard, not a
  restart.

``faultsim``/``retry`` are import-light (hot paths import them);
``checkpoint``/``preempt``/``elastic`` load lazily because they pull
in the ndarray/jax stack.
"""
from . import faultsim  # noqa: F401
from .retry import retry_call  # noqa: F401

__all__ = ["faultsim", "retry_call", "checkpoint", "preempt",
           "elastic", "CheckpointManager", "PreemptionDrain",
           "atomic_write_bytes", "restore_rng"]


def __getattr__(name):
    if name in ("checkpoint", "preempt", "elastic"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    if name in ("CheckpointManager", "atomic_write_bytes",
                "capture_rng", "restore_rng"):
        from . import checkpoint as _ckpt

        val = getattr(_ckpt, name)
        globals()[name] = val
        return val
    if name == "PreemptionDrain":
        from .preempt import PreemptionDrain

        globals()[name] = PreemptionDrain
        return PreemptionDrain
    raise AttributeError(
        f"module 'mxnet_tpu.resilience' has no attribute {name!r}")
