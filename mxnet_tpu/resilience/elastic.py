"""Elastic multi-host runtime: reshard-on-resize resume.

MXNet's L5 distributed layer (SURVEY §L5, ps-lite) let a job survive a
changing worker set — dead-node detection, re-registration, server-side
state that outlives any one worker.  This module is the TPU-native
analog, built so that **losing k hosts is a reshard, not a restart**:

* :func:`elastic_init` — multi-process bring-up over
  ``jax.distributed.initialize`` (coordinator address and process
  id/count resolved from explicit args, the ``MXNET_*`` knobs, or the
  legacy ``DMLC_*`` launcher contract), with a bounded-retry barrier
  (:mod:`.retry`) so a flaky coordinator or a slow-starting peer is a
  backoff, not a crash.  The CPU backend is first-class (gloo
  cross-process collectives), so the whole path is testable on a
  laptop with 2 subprocesses.
* :func:`topology_block` — the checkpoint manifest's ``topology``
  stamp: world size, process count, mesh shape, optimizer-sharding
  mode, bucket-plan fingerprint and the global batch.  A resume at a
  *different* world size detects the mismatch from this block alone.
* :func:`reshard_verdict` — the resize decision: compares the stamped
  topology with the live one and says whether optimizer state must
  re-shard (``plan_buckets`` re-run at the new shard count) and
  whether the batch cursor transfers.  Same-N resume is a verdict-level
  no-op — no gratuitous reshard.
* :func:`reslice_cursor` — the PR-3 batch cursor re-sliced across a
  new data-mesh width: cursors are kept in GLOBAL batches of a fixed
  global batch size, so the re-slice is a validation + identity, and
  :class:`ElasticHostIter` deterministically re-partitions the global
  sample stream over the new host set (no sample dropped or
  double-fed).
* :func:`host_gather` — one host copy of any jax array regardless of
  process span (fully-addressable, fully-replicated multi-process, or
  sharded multi-process via ``multihost_utils.process_allgather``) —
  what lets the PR-3 checkpoint writer stay world-size-agnostic on a
  real multi-host mesh.

Fault points (``resilience.faultsim``): ``dist.init`` fires inside
every initialize attempt (an armed ``raise`` exercises the retry
path end-to-end), ``dist.collective`` fires at the barrier and before
every sharded optimizer exchange (mid-step collective loss).
"""
from __future__ import annotations

import dataclasses
import os

import numpy as onp

from ..base import MXNetError
from . import faultsim
from .retry import retry_call

__all__ = ["ElasticContext", "elastic_enabled", "elastic_init",
           "initialized", "context", "elastic_mesh", "host_gather",
           "topology_block", "reshard_verdict", "reslice_cursor",
           "ElasticHostIter"]


@dataclasses.dataclass(frozen=True)
class ElasticContext:
    """One process's view of the elastic job after bring-up."""

    coordinator: str | None   # host:port, None for single-process
    num_processes: int
    process_id: int
    world_devices: int        # devices across every process
    local_devices: int
    backend: str

    @property
    def is_coordinator(self):
        return self.process_id == 0

    @property
    def distributed(self):
        return self.coordinator is not None


_STATE = {"ctx": None}


def _env_or(name, dmlc, cast, sentinel):
    """Resolve one bring-up knob: MXNET_* first, the legacy DMLC_*
    launcher contract second (tools/launch.py exports those)."""
    from ..config import get_env

    v = get_env(name)
    if v != sentinel:
        return cast(v)
    raw = os.environ.get(dmlc)
    if raw is not None:
        return cast(raw)
    return None


def elastic_enabled():
    """Whether multi-process bring-up is requested: ``MXNET_ELASTIC``
    set, or an explicit coordinator in the env (``MXNET_COORDINATOR``
    / a ``DMLC_NUM_WORKER > 1`` launcher contract)."""
    from ..config import get_env

    if get_env("MXNET_ELASTIC"):
        return True
    if get_env("MXNET_COORDINATOR"):
        return True
    try:
        return int(os.environ.get("MXNET_NUM_PROCESSES",
                                  os.environ.get("DMLC_NUM_WORKER", 1))
                   ) > 1
    except ValueError:
        return False


def initialized():
    return _STATE["ctx"] is not None


def context():
    """The live :class:`ElasticContext`, or None before bring-up."""
    return _STATE["ctx"]


def _resolve_bringup(coordinator, num_processes, process_id):
    from ..config import get_env

    if coordinator is None:
        coordinator = get_env("MXNET_COORDINATOR") or None
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT")
        # the DMLC contract only implies a coordinator when a launcher
        # actually exported a >1 worker job
        if uri and port and int(os.environ.get("DMLC_NUM_WORKER",
                                               "1")) > 1:
            coordinator = f"{uri}:{port}"
    if num_processes is None:
        num_processes = _env_or("MXNET_NUM_PROCESSES",
                                "DMLC_NUM_WORKER", int, 0)
    if process_id is None:
        process_id = _env_or("MXNET_PROCESS_ID", "DMLC_WORKER_ID",
                             int, -1)
    return coordinator, num_processes, process_id


def elastic_init(coordinator=None, num_processes=None, process_id=None,
                 attempts=None, timeout_sec=None, barrier=True):
    """Multi-process bring-up (idempotent; returns the live context).

    Wraps ``jax.distributed.initialize`` with:

    * knob resolution — explicit args > ``MXNET_COORDINATOR`` /
      ``MXNET_NUM_PROCESSES`` / ``MXNET_PROCESS_ID`` > the ``DMLC_*``
      launcher contract;
    * CPU-backend multiprocess support (gloo collectives) so the whole
      elastic path runs under 2 plain subprocesses in tests;
    * a bounded-retry loop (``MXNET_DIST_INIT_ATTEMPTS`` attempts
      within ``MXNET_DIST_INIT_TIMEOUT_SEC`` total) around the
      initialize call — the ``dist.init`` fault point fires inside
      every attempt, so an armed flake is retried exactly like a real
      coordinator hiccup;
    * an optional collective barrier proving cross-process collectives
      actually work before any training state is built (the
      ``dist.collective`` fault point fires here too).

    Single-process jobs (no coordinator resolvable, process count
    <= 1) skip ``jax.distributed`` entirely and return a local
    context — callers can use one code path for both shapes.
    """
    if _STATE["ctx"] is not None:
        return _STATE["ctx"]
    from ..config import get_env

    coordinator, num_processes, process_id = _resolve_bringup(
        coordinator, num_processes, process_id)
    import jax

    if coordinator is None and (num_processes or 1) > 1:
        # the silent version of this misconfiguration is two (or N)
        # world-size-1 jobs each believing it is rank 0, training the
        # full dataset independently and overwriting each other's
        # checkpoints — raise like the inverse case below does
        raise MXNetError(
            f"elastic_init: num_processes={num_processes} but no "
            "coordinator resolved (set MXNET_COORDINATOR=host:port or "
            "the DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT launcher "
            "contract)")
    if coordinator is None or (num_processes or 1) <= 1:
        ctx = ElasticContext(
            coordinator=None, num_processes=1, process_id=0,
            world_devices=jax.device_count(),
            local_devices=jax.local_device_count(),
            backend=jax.default_backend())
        _STATE["ctx"] = ctx
        return ctx
    if num_processes is None or process_id is None or process_id < 0:
        raise MXNetError(
            "elastic_init: a coordinator was resolved "
            f"({coordinator!r}) but num_processes/process_id were not "
            "(set MXNET_NUM_PROCESSES/MXNET_PROCESS_ID or the DMLC_* "
            "launcher contract)")
    try:
        # CPU cross-process collectives (the test backend) need gloo;
        # knob absent on jax builds where CPU collectives are default
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass

    attempts = int(attempts if attempts is not None
                   else get_env("MXNET_DIST_INIT_ATTEMPTS"))
    timeout_sec = float(timeout_sec if timeout_sec is not None
                        else get_env("MXNET_DIST_INIT_TIMEOUT_SEC"))

    def once():
        faultsim.inject("dist.init")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(num_processes),
            process_id=int(process_id))

    def on_retry(attempt, exc):
        try:
            from .. import telemetry

            telemetry.count("dist_init_retries")
            telemetry.event("dist_init_retry", attempt=attempt,
                            error=type(exc).__name__,
                            coordinator=coordinator)
        except Exception:
            pass
        try:  # a half-initialized client must not poison the redial
            jax.distributed.shutdown()
        except Exception:
            pass

    retry_call(once,
               retry_on=(RuntimeError, ConnectionError, OSError,
                         faultsim.FaultInjected),
               attempts=attempts, base_delay=0.2, max_delay=5.0,
               deadline_sec=timeout_sec, on_retry=on_retry)
    ctx = ElasticContext(
        coordinator=str(coordinator), num_processes=int(num_processes),
        process_id=int(process_id),
        world_devices=jax.device_count(),
        local_devices=jax.local_device_count(),
        backend=jax.default_backend())
    _STATE["ctx"] = ctx
    if barrier:
        elastic_barrier()
    try:
        from .. import telemetry

        telemetry.event("elastic_init", coordinator=ctx.coordinator,
                        num_processes=ctx.num_processes,
                        process_id=ctx.process_id,
                        world_devices=ctx.world_devices)
    except Exception:
        pass
    return ctx


def elastic_barrier():
    """A real collective across every process: psum of ones over all
    devices must equal the world device count.  Proves the mesh is
    live before any training state is sharded over it (a dead peer
    surfaces here, in seconds, not mid-epoch)."""
    import jax
    import jax.numpy as jnp

    faultsim.inject("dist.collective")
    n = jax.device_count()
    if n <= 1:
        return 1
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel import compat_shard_map

    mesh = elastic_mesh()
    ones = jax.make_array_from_callback(
        (n,), NamedSharding(mesh, P("data")),
        lambda idx: onp.ones((n,), onp.float32)[idx])
    mapped = compat_shard_map(
        lambda a: jax.lax.psum(a, "data"), mesh,
        in_specs=P("data"), out_specs=P())
    total = int(onp.asarray(
        jax.jit(mapped)(ones).addressable_data(0)).reshape(-1)[0])
    if total != n:
        raise MXNetError(
            f"elastic barrier psum returned {total}, want {n} — the "
            "cross-process collective mesh is not healthy")
    return total


def elastic_mesh(dp=None, tp=1, devices=None):
    """A dp×tp mesh spanning every process's devices (``jax.devices()``
    is global after ``elastic_init``).  ``tp=1`` (the default) returns
    the flat 1-D ``('data',)`` mesh every data-parallel artifact in
    this repo uses; ``dp`` defaults to world_devices // tp."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    tp = max(1, int(tp))
    if dp is None:
        dp = len(devices) // tp
    dp = int(dp)
    if dp * tp != len(devices):
        raise MXNetError(
            f"elastic_mesh: dp({dp}) x tp({tp}) != {len(devices)} "
            "devices")
    if tp == 1:
        return Mesh(onp.array(devices), ("data",))
    return Mesh(onp.array(devices).reshape(dp, tp), ("data", "model"))


def host_gather(x):
    """One host numpy copy of any jax array, regardless of process
    span: fully-addressable arrays copy directly, fully-replicated
    multi-process arrays read their local replica, and sharded
    multi-process arrays all-gather (``multihost_utils``).  The
    checkpoint writer routes every mesh-backed array through here, so
    the on-disk layout stays the world-size-agnostic single-array one
    at ANY world size."""
    if not hasattr(x, "is_fully_addressable"):
        return onp.asarray(x)
    if x.is_fully_addressable:
        return onp.asarray(x)
    if getattr(x, "is_fully_replicated", False):
        return onp.asarray(x.addressable_data(0))
    from jax.experimental import multihost_utils

    return onp.asarray(multihost_utils.process_allgather(x, tiled=True))


# ------------------------------------------------------------- topology
def topology_block(world_size=None, num_processes=None, mesh=None,
                   sharding="none", plan=None, global_batch=None,
                   zero_stage=None):
    """The checkpoint manifest's ``topology`` stamp.

    ``world_size`` is the optimizer-shard count (the data-mesh width);
    ``plan`` (a ``parallel.zero`` bucket list) contributes its
    fingerprint so a resume can tell "same shard count, same packing"
    from "must re-plan" without loading any state.  ``zero_stage``
    rides both the stamp and the fingerprint: stage 3 persists
    PARAMETER shards in the flat-bucket layout, so its checkpoints
    must never silently resume into a replicated-param world (use
    ``sharding="zero3"`` there; stages 1/2 keep the historic "ps"
    stamp and fingerprint — their payloads are interchangeable)."""
    if mesh is not None:
        if world_size is None:
            world_size = int(mesh.shape.get("data", mesh.devices.size))
        mesh_shape = tuple(int(s) for s in mesh.devices.shape)
        mesh_axes = tuple(str(a) for a in mesh.axis_names)
    else:
        mesh_shape = (int(world_size),) if world_size else (1,)
        mesh_axes = ("data",)
    if world_size is None:
        world_size = 1
    if num_processes is None:
        ctx = _STATE["ctx"]
        num_processes = ctx.num_processes if ctx is not None else 1
    block = {
        "world_size": int(world_size),
        "num_processes": int(num_processes),
        "mesh_shape": list(mesh_shape),
        "mesh_axes": list(mesh_axes),
        "sharding": str(sharding),
    }
    if zero_stage is not None:
        block["zero_stage"] = int(zero_stage)
    if plan is not None:
        from ..parallel.zero import plan_fingerprint

        block["plan_fingerprint"] = plan_fingerprint(plan, world_size,
                                                     zero_stage)
        block["n_buckets"] = len(plan)
    if global_batch is not None:
        block["global_batch"] = int(global_batch)
    return block


def reshard_verdict(old, new):
    """The resize decision for a resume: given the checkpoint's
    ``topology`` block and the live one, say whether optimizer state
    must re-shard and whether the batch cursor transfers.

    * equal world size AND equal plan fingerprint → ``reshard: False``
      (same-N resume is a no-op: no gratuitous gather/replan/scatter
      verdict, ``set_states`` just places the shards);
    * anything that changes the shard layout (world size, mesh shape,
      sharding mode, bucket plan) → ``reshard: True`` with the reasons
      listed;
    * ``cursor_compatible`` is False only when both sides stamped a
      global batch and they differ — the cursor is kept in GLOBAL
      batches, which only re-slice cleanly at a fixed global batch.
    """
    old = dict(old or {})
    new = dict(new or {})
    reasons = []
    for key, label in (("world_size", "world size"),
                       ("mesh_shape", "mesh shape"),
                       ("sharding", "sharding mode"),
                       ("plan_fingerprint", "bucket plan")):
        a, b = old.get(key), new.get(key)
        if a is not None and b is not None and a != b:
            reasons.append(f"{label} {a!r} -> {b!r}")
    gb_old, gb_new = old.get("global_batch"), new.get("global_batch")
    cursor_ok = not (gb_old is not None and gb_new is not None
                     and int(gb_old) != int(gb_new))
    return {
        "reshard": bool(reasons),
        "reasons": reasons,
        "old_world": old.get("world_size"),
        "new_world": new.get("world_size"),
        "cursor_compatible": cursor_ok,
    }


def reslice_cursor(batch_cursor, old, new):
    """Re-slice the PR-3 batch cursor across a new data-mesh width.

    Cursors count GLOBAL batches of a fixed global batch size, so the
    number of consumed batches is invariant under a resize — each host
    of the new world skips exactly ``batch_cursor`` batches of its own
    re-sliced stream (:class:`ElasticHostIter` makes that slicing
    deterministic).  What CANNOT transfer is a cursor across a global
    batch-size change: the sample boundary would land mid-batch, so
    that raises instead of silently dropping or double-feeding
    samples."""
    batch_cursor = int(batch_cursor)
    if batch_cursor == 0:
        return 0
    v = reshard_verdict(old, new)
    if not v["cursor_compatible"]:
        raise MXNetError(
            "cannot re-slice a mid-epoch batch cursor across a global "
            f"batch change ({dict(old or {}).get('global_batch')} -> "
            f"{dict(new or {}).get('global_batch')}): the sample "
            "boundary would land mid-batch.  Resume from an "
            "epoch-boundary checkpoint, or keep the global batch "
            "fixed across the resize.")
    return batch_cursor


class ElasticHostIter:
    """Deterministic per-host re-slicing of a global batch stream.

    Wraps an iterator yielding GLOBAL batches (e.g. an ``NDArrayIter``
    at the fixed global batch size, same seed on every host) and
    yields this host's contiguous row slice of each one:
    ``rows[rank * b_local : (rank + 1) * b_local]``.  Because the
    slicing is a pure function of (global batch index, rank,
    num_hosts), a resume at a different host count re-partitions the
    SAME global stream — the union over the new host set is exactly
    the global stream, so no sample is dropped or double-fed, and a
    cursor of k global batches means "skip k batches of your own
    stream" on every host of any world size.
    """

    def __init__(self, base, rank, num_hosts):
        self.base = base
        self.rank = int(rank)
        self.num_hosts = max(1, int(num_hosts))
        if not 0 <= self.rank < self.num_hosts:
            raise MXNetError(
                f"ElasticHostIter: rank {rank} outside "
                f"[0, {num_hosts})")

    def _slice_desc(self, descs):
        out = []
        for d in descs:
            name, shape = d[0], tuple(d[1])
            if shape[0] % self.num_hosts:
                raise MXNetError(
                    f"global batch {shape[0]} of {name!r} must divide "
                    f"the {self.num_hosts}-host world")
            out.append((name, (shape[0] // self.num_hosts,)
                        + shape[1:]))
        return out

    @property
    def provide_data(self):
        return self._slice_desc(self.base.provide_data)

    @property
    def provide_label(self):
        return self._slice_desc(self.base.provide_label)

    def reset(self):
        self.base.reset()

    def _slice(self, arr):
        n = arr.shape[0]
        if n % self.num_hosts:
            raise MXNetError(
                f"global batch {n} must divide the "
                f"{self.num_hosts}-host world")
        b = n // self.num_hosts
        return arr[self.rank * b:(self.rank + 1) * b]

    def _slice_any(self, a):
        if hasattr(a, "_data"):  # NDArray: slice the backing array
            from .. import ndarray as nd

            return nd.NDArray(self._slice(a._data))
        return self._slice(onp.asarray(a))

    def _local_pad(self, global_pad, global_n):
        """This host's share of the global batch's pad count.  Padding
        rows live at the TAIL of the global batch, so only the hosts
        whose row range overlaps ``[global_n - pad, global_n)`` carry
        any — propagating the global count unchanged would make
        downstream pad-trimming (``BaseModule.predict``) discard real
        samples on the early hosts."""
        global_pad = int(global_pad or 0)
        if not global_pad:
            return 0
        b = global_n // self.num_hosts
        end = (self.rank + 1) * b
        return max(0, min(b, end - (global_n - global_pad)))

    def __iter__(self):
        for batch in self.base:
            if hasattr(batch, "data"):  # DataBatch
                from ..io import DataBatch

                yield DataBatch(
                    data=[self._slice_any(a) for a in batch.data],
                    label=[self._slice_any(a)
                           for a in (batch.label or [])] or None,
                    pad=self._local_pad(
                        getattr(batch, "pad", 0),
                        int(batch.data[0].shape[0])))
            else:  # raw (x, y) tuples
                yield tuple(self._slice(onp.asarray(a))
                            for a in batch)

    def next(self):
        if not hasattr(self, "_it"):
            self._it = iter(self)
        try:
            return next(self._it)
        except StopIteration:
            del self._it
            raise
