"""Deterministic fault injection (``MXNET_FAULT_SPEC``).

The reference has no fault-injection harness anywhere (SURVEY.md §5.3:
elasticity is a flat NO; ps-lite offers dead-node *detection* only).
This registry gives every resilience-critical code path a NAMED
injection point that production traffic pays one dict lookup for, and
tests arm deterministically by hit-count — "crash during the 3rd
checkpoint write" becomes a reproducible scenario instead of a
``kill -9`` race.

Point names are REGISTERED, not free-form: the in-tree points below
are built in, and subsystems add their own at import time via
:func:`register_point` (``mxnet_tpu.serving`` registers ``serve.*``
this way) — so a spec naming an unknown point fails LOUDLY at arm
time (``reset``/first ``inject``) instead of silently never firing, a
typo'd drill can no longer green-pass by injecting nothing.  Arm the
spec after importing the subsystem that registers the point.

Points wired in-tree:

==============  =======================================================
``feed.h2d``    io/device_feed.py producer, before each H2D transfer
``ps.push``     _ps.py client, inside every push/spush attempt
``ps.pull``     _ps.py client, inside every pull/spull attempt
``ckpt.write``  resilience/checkpoint.py, MID-payload in atomic_write
``step.loss_nan``  make_train_step host wrapper + Module.fit step guard
``bench.stall``  bench.py after the measure phase (a ``delay`` here
                 wedges the harness with NO heartbeats — the watchdog
                 stall-path test point)
``dist.init``   resilience/elastic.py, inside every
                ``jax.distributed.initialize`` attempt (a ``raise``
                exercises the bring-up retry loop end-to-end)
``dist.collective``  elastic's bring-up barrier + the sharded
                optimizer exchange (ShardedBucketUpdater.update_all),
                BEFORE the jitted collective program — the mid-step
                collective-loss simulation for resize drills
``serve.admit``  serving/server.py, inside every admission decision
                (registered by ``mxnet_tpu.serving`` at import)
``serve.batch``  serving/server.py batcher, before each dispatched
                microbatch (registered by ``mxnet_tpu.serving``)
``serve.model``  serving/server.py, inside every model invocation —
                ``delay`` = a slow model, ``raise`` = a transient
                failure the retry budget absorbs, ``nan`` = poisoned
                outputs the breaker counts, ``crash`` = hard death
                mid-traffic (registered by ``mxnet_tpu.serving``)
``fleet.route``  serving/fleet.py FleetRouter.submit, inside every
                routing decision (registered by ``mxnet_tpu.serving``)
``fleet.replica``  serving/frontend.py, inside every replica predict
                request — ``crash`` armed in ONE replica's env is the
                deterministic mid-burst replica death the fleet
                drills route around (registered by
                ``mxnet_tpu.serving``)
``fleet.swap``  serving/fleet.py ModelHost.swap, before the next
                artifact loads — ``crash`` = mid-swap replica death
                (registered by ``mxnet_tpu.serving``)
``peer.heartbeat``  resilience/healing.py Heartbeater, inside every
                beat — ``delay`` = a stalled heart the peers' failure
                detectors must flag, ``raise`` = one dropped beat
                (absorbed), ``crash`` = sudden death mid-beat
``ckpt.async``  resilience/checkpoint.py async snapshot writer,
                MID-payload in every atomic write of a ``save_async``
                version — ``crash`` must leave latest == previous-good
``heal.relaunch``  resilience/healing.py supervisor, before every
                respawn of the training command (``raise`` aborts the
                respawn policy, ``delay`` = slow scheduler)
``io.read``     recordio.py MXRecordIO.read, per record read — a
                ``raise`` is a torn frame mid-stream: strict readers
                propagate it, resync readers skip to the next magic
                boundary and report the gap
``io.decode``   io/image_record_iter.py, per record unpack+decode — a
                ``raise`` is one undecodable record the pipeline must
                QUARANTINE (skip + manifest + counter), never an
                epoch kill
``io.worker``   io/image_record_iter.py worker pool, per claimed
                batch, consumed via :func:`probe` — ``crash`` kills
                the WORKER THREAD holding the batch (the pool's
                SIGKILL analog: surviving a worker death is the whole
                point, so the process must not die), ``raise`` is a
                logged worker abort, ``delay`` a straggler/wedge the
                per-batch deadline re-dispatches around
==============  =======================================================

Spec grammar (env ``MXNET_FAULT_SPEC`` or ``faultsim.reset(spec)``)::

    spec   := clause (';' clause)*
    clause := point ':' action ['=' value] '@' hits
    action := crash | raise | delay | nan
    hits   := N | N-M | N+          (1-based per-point hit count)

Actions:

* ``crash``   — ``os._exit(87)``: a hard kill, no cleanup/atexit runs
  (the mid-write power-loss simulation).
* ``raise``   — raise :class:`FaultInjected` (retry paths list it as
  transient, so backoff recovery is exercised for real).
* ``delay=S`` — ``time.sleep(S)``.
* ``nan``     — return ``"nan"``: the caller poisons its own value
  (used by the step-level NaN guard paths).

Example: ``MXNET_FAULT_SPEC="ckpt.write:crash@3;ps.push:delay=2.0@7"``
crashes the process in the middle of the 3rd checkpoint payload write
and delays the 7th PS push by 2 seconds.
"""
from __future__ import annotations

import os
import threading
import time

from ..base import MXNetError

__all__ = ["FaultInjected", "inject", "probe", "reset", "hits",
           "armed", "on_crash", "register_point", "points",
           "CRASH_EXIT_CODE"]

#: exit status of an armed ``crash`` action — distinguishable from a
#: real signal kill in subprocess tests
CRASH_EXIT_CODE = 87

#: name -> one-line doc of every arm-able injection point.  The
#: in-tree points are built in; subsystems extend the set at import
#: time via :func:`register_point` so ``MXNET_FAULT_SPEC`` validation
#: tracks what is actually wired, not a hard-coded list.
_POINTS = {
    "feed.h2d": "device-feed producer, before each H2D transfer",
    "ps.push": "PS client, inside every push/spush attempt",
    "ps.pull": "PS client, inside every pull/spull attempt",
    "ckpt.write": "mid-payload in checkpoint atomic_write",
    "step.loss_nan": "train-step host wrapper + fit step guard",
    "bench.stall": "bench.py after the measure phase",
    "dist.init": "inside every jax.distributed.initialize attempt",
    "dist.collective": "before the jitted collective program",
    "peer.heartbeat": "healing Heartbeater, inside every beat "
                      "(delay = a stalled heart, raise = one dropped "
                      "beat)",
    "ckpt.async": "async snapshot writer thread, mid-payload in every "
                  "atomic write of a save_async version",
    "heal.relaunch": "healing supervisor, before every respawn of the "
                     "training command",
    "io.read": "MXRecordIO.read, per record — raise = a torn frame "
               "(resync readers skip to the next magic boundary)",
    "io.decode": "record iterator, per record unpack+decode — raise = "
                 "one undecodable record (quarantined, never fatal)",
    "io.worker": "data-plane worker pool, per claimed batch (probe "
                 "semantics: crash kills the worker THREAD, not the "
                 "process)",
}


def register_point(name, doc=""):
    """Register a runtime injection point name so specs may arm it.

    Subsystems outside resilience (serving's ``serve.*`` points) call
    this at import time; a spec clause naming an UNREGISTERED point
    raises :class:`MXNetError` at arm time — a typo'd drill must fail
    loudly, not green-pass by never injecting.  Idempotent; returns
    ``name`` so it can be used in assignments."""
    with _LOCK:
        _POINTS[str(name)] = str(doc)
    return name


def points():
    """Sorted names of every registered injection point."""
    with _LOCK:
        return sorted(_POINTS)


class FaultInjected(Exception):
    """Raised by an armed ``raise`` injection point."""

    def __init__(self, point, hit):
        super().__init__(f"injected fault at {point!r} (hit {hit})")
        self.point = point
        self.hit = hit


class _Rule:
    __slots__ = ("action", "value", "lo", "hi")

    def __init__(self, action, value, lo, hi):
        self.action = action
        self.value = value
        self.lo = lo
        self.hi = hi  # None = open-ended (the "N+" form)

    def matches(self, n):
        return self.lo <= n and (self.hi is None or n <= self.hi)


_LOCK = threading.Lock()
# spec None = not yet armed (first inject() reads MXNET_FAULT_SPEC)
_STATE = {"spec": None, "rules": {}, "hits": {}}

#: callbacks run on the ``crash`` path between the flight dump and
#: ``os._exit`` — ``os._exit`` skips atexit AND every other thread's
#: pending work, so state that must survive the simulated power loss
#: (bench.py's partial headline JSON, armed from the main thread while
#: the crash can fire on any thread) registers a flusher here
_CRASH_HOOKS = []


def on_crash(fn):
    """Register ``fn()`` to run right before a ``crash`` action's
    ``os._exit`` (after the flight dump).  Hooks must be fast and
    exception-safe conceptually; any raise is swallowed — the crash
    must fire even if a hook is broken.  Returns ``fn`` so it can be
    used as a decorator."""
    if fn not in _CRASH_HOOKS:
        _CRASH_HOOKS.append(fn)
    return fn


def _parse(spec):
    rules = {}
    for clause in filter(None, (c.strip() for c in spec.split(";"))):
        try:
            point, rest = clause.split(":", 1)
            action, hitpart = rest.split("@", 1)
        except ValueError:
            raise MXNetError(
                f"bad fault spec clause {clause!r} "
                "(want point:action[=value]@hits)") from None
        value = None
        if "=" in action:
            action, raw = action.split("=", 1)
            try:
                value = float(raw)
            except ValueError:
                raise MXNetError(
                    f"bad fault value {raw!r} in {clause!r}") from None
        action = action.strip()
        if action not in ("crash", "raise", "delay", "nan"):
            raise MXNetError(f"unknown fault action {action!r} in "
                             f"{clause!r}")
        hitpart = hitpart.strip()
        try:
            if hitpart.endswith("+"):
                lo, hi = int(hitpart[:-1]), None
            elif "-" in hitpart:
                a, b = hitpart.split("-", 1)
                lo, hi = int(a), int(b)
            else:
                lo = hi = int(hitpart)
        except ValueError:
            raise MXNetError(
                f"bad hit range {hitpart!r} in {clause!r}") from None
        point = point.strip()
        if point not in _POINTS:
            known = ", ".join(sorted(_POINTS))
            raise MXNetError(
                f"unknown fault point {point!r} in {clause!r} "
                f"(registered points: {known}; subsystems register "
                "theirs via faultsim.register_point at import — arm "
                "the spec after importing them)")
        rules.setdefault(point, []).append(
            _Rule(action, value, lo, hi))
    return rules


def reset(spec=None):
    """(Re)arm from ``spec`` and clear all hit counters.

    ``spec=None`` re-reads ``MXNET_FAULT_SPEC``; tests usually pass the
    spec explicitly so arming happens at a precise program point rather
    than at process start.
    """
    if spec is None:
        spec = os.environ.get("MXNET_FAULT_SPEC", "")
    rules = _parse(spec)
    with _LOCK:
        _STATE["spec"] = spec
        _STATE["rules"] = rules
        _STATE["hits"] = {}


def _ensure_locked():
    if _STATE["spec"] is None:
        spec = os.environ.get("MXNET_FAULT_SPEC", "")
        # parse BEFORE mutating state: an unknown-point spec (armed
        # from the env before the registering subsystem imported) must
        # stay LOUD on every later call — recording the spec first
        # would swallow the error once and silently disarm the drill
        rules = _parse(spec)
        _STATE["spec"] = spec
        _STATE["rules"] = rules
        _STATE["hits"] = {}


def hits(point):
    """How many times ``point`` has fired since the last reset()."""
    with _LOCK:
        _ensure_locked()
        return _STATE["hits"].get(point, 0)


def armed(point):
    """True when any clause names ``point`` — the cheap pre-check that
    keeps optional wrappers (the make_train_step NaN poisoner) off the
    fast path entirely when the harness is disarmed."""
    with _LOCK:
        _ensure_locked()
        return point in _STATE["rules"]


def _fire(point):
    """Count a hit at ``point``, match the armed rule and emit the
    fault telemetry — the ONE core both :func:`probe` and
    :func:`inject` build on (the two entry points must not drift).
    Returns ``(rule, hit_number)``; rule is None when nothing armed
    matches."""
    with _LOCK:
        _ensure_locked()
        n = _STATE["hits"].get(point, 0) + 1
        _STATE["hits"][point] = n
        rule = None
        for r in _STATE["rules"].get(point, ()):
            if r.matches(n):
                rule = r
                break
    if rule is not None:
        try:
            # armed hits are rare: telemetry cost only ever lands on
            # the fault path, never on the per-call fast path above
            from .. import telemetry

            telemetry.count("faults")
            telemetry.event("fault", point=point, action=rule.action,
                            hit=n)
        except Exception:
            pass  # the harness must fire even if telemetry is broken
    return rule, n


def probe(point):
    """Count a hit at ``point`` and return the armed action NAME
    ('crash' / 'raise' / 'delay' / 'nan', or None) without executing
    ``crash``/``raise``/``nan`` — for points whose CALLER owns the
    blast radius.  The data-plane worker pool is the motivating case:
    an ``io.worker:crash`` must kill the worker THREAD that hit it
    (the pool's SIGKILL analog — surviving a worker death is the
    feature under test), where :func:`inject`'s crash would
    ``os._exit`` the whole training process.  ``delay`` is slept here
    so straggler semantics stay uniform with inject(); telemetry
    counts the fault the same way."""
    rule, _ = _fire(point)
    if rule is None:
        return None
    if rule.action == "delay":
        time.sleep(rule.value or 0.0)
    return rule.action


def inject(point):
    """Count a hit at ``point`` and fire the armed action, if any.

    Returns ``"nan"`` when the caller must poison its value, else
    ``None``.  Thread-safe: producer threads and PS serve threads share
    one counter per point, so hit numbering is global per process.
    """
    rule, n = _fire(point)
    if rule is None:
        return None
    if rule.action == "crash":
        try:
            # os._exit skips atexit: the flight recorder is the ONLY
            # record the simulated power loss leaves behind
            from .. import telemetry

            telemetry.flight_dump(f"fault_crash:{point}")
        except Exception:
            pass
        # last-gasp flushers (bench partial JSON, ...): os._exit gives
        # no other thread a chance to finish a pending write, so
        # whatever must be parseable after the "power loss" flushes
        # here, synchronously, on the crashing thread
        for hook in list(_CRASH_HOOKS):
            try:
                hook()
            except Exception:
                pass
        os._exit(CRASH_EXIT_CODE)
    if rule.action == "raise":
        raise FaultInjected(point, n)
    if rule.action == "delay":
        time.sleep(rule.value or 0.0)
        return None
    return "nan"
