"""Self-healing training runtime: peer failure detection, automatic
reshard-on-death, and the supervisor that owns the respawn policy.

PR 7 made losing k hosts "a reshard, not a restart" — but only for a
*cooperative* loss: a SIGTERM the :class:`~.preempt.PreemptionDrain`
catches.  A host that dies abruptly (SIGKILL, OOM kill, network
partition) leaves the survivors wedged inside a collective until the
watchdog dumps stacks; the reference's ps-lite layer tracks exactly
this liveness at its server (SURVEY §L5: dead workers detected by the
PS, their keys re-pulled).  This module is that capability for the
TPU-native runtime:

* **Peer liveness** — every process runs a :class:`Heartbeater`
  (a daemon thread renewing a per-rank heartbeat file under a shared
  directory; the ``peer.heartbeat`` fault point fires per beat so a
  ``delay`` spec is a provably stalled heart) and a
  :class:`FailureDetector` that declares a peer dead when its beat
  goes stale for ``MXNET_PEER_TIMEOUT_SEC`` — or IMMEDIATELY when the
  beat's recorded pid is gone on the same host (the SIGKILL drill's
  fast path: detection latency is the detector poll, not the timeout).
* **Collective abandonment** — :func:`guard_collective` runs a
  collective-bearing callable on a worker thread while the caller
  polls the detector: a peer death surfaces as :class:`PeerDeadError`
  on the survivor's thread even when the psum underneath would block
  forever (the wedged native call is abandoned on its daemon thread).
  Backends that *raise* on a broken mesh (gloo's connection-reset) are
  translated to the same :class:`PeerDeadError` when the detector
  confirms a dead peer, so callers handle ONE exception either way.
* **Automatic reshard-on-death** — on a declared death the survivor
  fires the **emergency checkpoint** (the freshest host-side snapshot
  registered via :func:`register_emergency` — typically
  ``CheckpointManager.flush_emergency``; a snapshot needs NO
  collectives, which is the whole point: the mesh is already broken),
  emits a ``heal`` record + ``peer_deaths`` counter, and exits with
  :data:`PEER_DEATH_EXIT_CODE` through :func:`heal_exit` —
  ``os._exit``, because a jax.distributed teardown with a dead peer
  wedges the interpreter's atexit.  The relaunch then resumes through
  the PR-7 reshard machinery at the surviving world size
  (``reshard_verdict`` + ``reslice_cursor``), bumping
  ``auto_reshards``.
* **Supervisor** — ``python -m mxnet_tpu.resilience.healing
  --relaunch -- CMD...`` owns the respawn policy: it spawns CMD,
  and when CMD dies with a healable status (peer death, a signal
  kill, the faultsim crash code) relaunches it up to
  ``MXNET_HEAL_MAX_RELAUNCH`` times with ``MXNET_HEAL_ATTEMPT``
  exported, so the command itself can choose the new world size
  (``surviving_ranks`` / ``elect_coordinator`` read the heartbeat
  directory).  The ``heal.relaunch`` fault point fires before every
  respawn.

Coordinator migration: rank 0 owns checkpoint writes in the drills;
when rank 0 itself dies, :func:`elect_coordinator` hands the role to
the LOWEST surviving rank — checkpoints are world-size-agnostic
single-array layouts (``host_gather``), so the file a migrated
coordinator writes is byte-compatible with a rank-0-written one
(asserted in tests).
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time

from ..base import MXNetError
from . import faultsim

__all__ = ["PEER_DEATH_EXIT_CODE", "PeerDeadError", "CollectiveTimeout",
           "Heartbeater", "FailureDetector", "guard_collective",
           "register_emergency", "fire_emergency", "heal_exit",
           "arm", "disarm", "session", "poll", "surviving_ranks",
           "elect_coordinator", "relaunch_attempt", "main"]

#: exit status of a survivor that detected a peer death and healed out
#: (emergency checkpoint flushed, telemetry closed) — the supervisor's
#: signal to relaunch at the surviving world size.  Distinct from the
#: faultsim crash code (87) and a watchdog abort.
PEER_DEATH_EXIT_CODE = 83

class PeerDeadError(MXNetError):
    """A peer process was declared dead by the failure detector."""

    def __init__(self, dead, detail=""):
        self.dead = sorted(int(d) for d in dead)
        msg = f"peer rank(s) {self.dead} declared dead"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class CollectiveTimeout(MXNetError):
    """A guarded collective exceeded its wait budget with every peer
    still nominally alive — the mesh is wedged, not dead."""


def _pid_alive(pid):
    """Whether a same-host pid is a LIVE process.  ``os.kill(pid, 0)``
    alone is not enough: a SIGKILLed child nobody has reaped yet is a
    zombie — signalable, but as dead as a peer can be (its sockets are
    closed, its collectives will never answer).  On Linux the
    ``/proc/<pid>/stat`` state field settles it; elsewhere the zombie
    ambiguity falls back to the staleness timeout."""
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but not ours
    try:
        with open(f"/proc/{int(pid)}/stat", "rb") as f:
            stat = f.read()
        # state is the first field after the comm's closing paren
        # (comm itself may contain spaces/parens)
        state = stat.rsplit(b")", 1)[1].split()[0]
        if state in (b"Z", b"X", b"x"):
            return False
    except (OSError, IndexError):
        pass  # no procfs: treat signalable as alive
    return True


# --------------------------------------------------------------- beats
def _hb_path(hb_dir, rank):
    return os.path.join(os.fspath(hb_dir), f"rank-{int(rank)}.hb")


def _write_beat(hb_dir, rank, step=None):
    """One atomic heartbeat: payload (pid/host/monotonic step) written
    to a temp file and renamed over ``rank-<r>.hb`` — a reader never
    sees a torn beat, and the file mtime IS the beat clock."""
    path = _hb_path(hb_dir, rank)
    os.makedirs(os.fspath(hb_dir), exist_ok=True)
    payload = {"rank": int(rank), "pid": os.getpid(),
               "host": socket.gethostname(), "time": time.time()}
    if step is not None:
        payload["step"] = int(step)
    # pid AND thread id: the daemon beater and an inline fit-poll beat
    # may race — two writers on one tmp path could promote a torn
    # beat, which a peer's detector reads as a sticky false death
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload))
    os.replace(tmp, path)
    return path


def _read_beat(hb_dir, rank):
    """(payload, age_seconds) of a rank's beat, or (None, None) when
    the rank has never beaten."""
    path = _hb_path(hb_dir, rank)
    try:
        age = time.time() - os.stat(path).st_mtime
        with open(path) as f:
            return json.loads(f.read()), age
    except (OSError, ValueError):
        return None, None


class Heartbeater:
    """Daemon thread renewing this process's heartbeat file every
    ``interval`` seconds.  ``beat()`` may also be called inline (step
    boundaries) to carry the current step number; the thread keeps the
    file fresh even when the main thread is wedged inside a collective
    — which is exactly when a SURVIVOR's liveness must stay provable to
    its peers."""

    def __init__(self, hb_dir, rank, interval=None):
        from ..config import get_env

        self.hb_dir = os.fspath(hb_dir)
        self.rank = int(rank)
        if interval is None:
            # beat several times per timeout window so one missed beat
            # (scheduler hiccup) is never a false death
            interval = max(0.05,
                           float(get_env("MXNET_PEER_TIMEOUT_SEC")) / 4)
        self.interval = float(interval)
        os.makedirs(self.hb_dir, exist_ok=True)
        self._stop = threading.Event()
        self._step = None
        self._last_write = 0.0
        self.beat()
        self._thread = threading.Thread(
            target=self._run, name="mxnet_tpu-heartbeat", daemon=True)
        self._thread.start()

    def beat(self, step=None):
        """Record liveness (and optionally the current step).  Inline
        callers (the per-batch fit poll) are RATE-LIMITED to half the
        beat interval: the daemon thread already keeps the file fresh,
        and a heartbeat dir on shared storage must not pay one
        rename per millisecond-scale step."""
        if step is not None:
            self._step = int(step)
        now = time.monotonic()
        if now - self._last_write < self.interval / 2:
            return
        self._last_write = now
        try:
            faultsim.inject("peer.heartbeat")
            _write_beat(self.hb_dir, self.rank, self._step)
        except faultsim.FaultInjected:
            pass  # an armed raise = one dropped beat, not a crash
        except OSError:
            pass  # a full disk must not kill the run; staleness will
            #       page through the peer's detector instead

    def _run(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            os.unlink(_hb_path(self.hb_dir, self.rank))
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class FailureDetector:
    """Survivor-side death verdicts over the heartbeat directory.

    A peer is DEAD when:

    * its beat file exists but has gone stale for longer than
      ``timeout`` (``MXNET_PEER_TIMEOUT_SEC``), or
    * its recorded pid no longer exists on this host (same-hostname
      beats only — the SIGKILL fast path: no waiting out the timeout
      for a local corpse), or
    * it NEVER beat within ``timeout`` of the detector starting (a
      peer that died before writing its first beat).

    ``dead_peers()`` is cheap (one stat per peer) and safe to poll
    from step loops and guard threads.  Verdicts are sticky: a rank
    once declared dead stays dead (a resurrected pid must rejoin as a
    NEW incarnation via relaunch, not un-declare its own death).
    """

    def __init__(self, hb_dir, rank, num_ranks, timeout=None,
                 telemetry=True):
        from ..config import get_env

        self.hb_dir = os.fspath(hb_dir)
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self.timeout = (float(get_env("MXNET_PEER_TIMEOUT_SEC"))
                        if timeout is None else float(timeout))
        # telemetry=False for QUERY-side detectors (surviving_ranks in
        # a relaunched child, the bench drill): the a0 survivor
        # already counted the death — a second detector re-observing
        # the same corpse must not double-count peer_deaths
        self.telemetry = bool(telemetry)
        self._t0 = time.time()
        self._host = socket.gethostname()
        self._dead = {}  # rank -> reason (sticky)
        self._first_mtime = {}  # rank -> mtime at first observation

    def _verdict(self, r):
        payload, age = _read_beat(self.hb_dir, r)
        if payload is None:
            if time.time() - self._t0 > self.timeout:
                return f"never beat within {self.timeout:.1f}s"
            return None
        try:
            mtime = os.stat(_hb_path(self.hb_dir, r)).st_mtime
        except OSError:
            mtime = None
        if mtime is not None:
            first = self._first_mtime.setdefault(r, mtime)
            if mtime == first and first < self._t0:
                # an UNCHANGED beat that predates this detector: a
                # leftover file from a previous incarnation (fit
                # never cleans the shared dir).  It earns the same
                # startup grace as a missing beat — the pid it names
                # belongs to the old world, so neither the pid fast
                # path nor plain staleness may execute a peer that is
                # merely still starting up.  Any mtime CHANGE is live
                # activity and restores the normal rules.
                if time.time() - self._t0 > self.timeout:
                    return (f"no fresh beat within "
                            f"{self.timeout:.1f}s (stale "
                            "pre-existing beat)")
                return None
        if payload.get("host") == self._host:
            pid = int(payload.get("pid", 0))
            if pid > 0 and not _pid_alive(pid):
                return f"pid {pid} gone"
        if age is not None and age > self.timeout:
            return f"beat stale {age:.1f}s > {self.timeout:.1f}s"
        return None

    def dead_peers(self):
        """Sorted ranks currently declared dead (never includes self)."""
        for r in range(self.num_ranks):
            if r == self.rank or r in self._dead:
                continue
            reason = self._verdict(r)
            if reason:
                self._dead[r] = reason
                if self.telemetry:
                    try:
                        from .. import telemetry

                        telemetry.count("peer_deaths")
                        telemetry.heal("peer_death", peer=r,
                                       rank=self.rank, detail=reason)
                    except Exception:
                        pass
        return sorted(self._dead)

    def reasons(self):
        return dict(self._dead)

    def check(self):
        """Raise :class:`PeerDeadError` if any peer is dead."""
        dead = self.dead_peers()
        if dead:
            raise PeerDeadError(dead, "; ".join(
                f"rank {r}: {why}" for r, why in self.reasons().items()))


def surviving_ranks(hb_dir, num_ranks, timeout=None, self_rank=None):
    """Ranks whose beats are live RIGHT NOW — what a relaunched
    supervisor child reads to size its new world.  A rank with a fresh
    beat and a live pid survives; everything else is counted out.
    ``self_rank`` is always a survivor: the caller IS that rank's new
    incarnation, and the beat file its dead predecessor left behind
    must not count the caller out of its own world."""
    det = FailureDetector(hb_dir,
                          rank=-1 if self_rank is None
                          else int(self_rank),
                          num_ranks=num_ranks, timeout=timeout,
                          telemetry=False)
    det._t0 = 0.0  # no startup grace: a missing beat is a dead rank
    dead = set(det.dead_peers())
    return [r for r in range(int(num_ranks)) if r not in dead]


def elect_coordinator(survivors):
    """Coordinator election after a death: the LOWEST surviving rank
    takes the role (deterministic, no communication needed — every
    survivor reaches the same verdict from the same heartbeat dir).
    Returns (coordinator_rank, my_new_process_id_map) where the map
    renumbers survivors contiguously from 0 — the shape
    ``elastic_init`` needs for the shrunken world."""
    survivors = sorted(int(s) for s in survivors)
    if not survivors:
        raise MXNetError("elect_coordinator: no survivors")
    return survivors[0], {old: new for new, old in enumerate(survivors)}


# ------------------------------------------------- guarded collectives
class _GuardWorker:
    """One reusable daemon thread executing guarded callables: fit
    wraps every step when healing is armed, and spawning two fresh
    threads per millisecond-scale batch is measurable churn.  A
    worker abandoned mid-call (wedged collective) is simply never
    returned to the pool — the next guard takes a fresh one, the
    wedged daemon thread dies with the process."""

    def __init__(self):
        import queue as _queue

        self._q = _queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name="mxnet_tpu-guard", daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            fn, result, error, done = self._q.get()
            try:
                result.append(fn())
            except BaseException as exc:  # noqa: BLE001 — re-raised
                error.append(exc)
            finally:
                done.set()

    def submit(self, fn):
        result, error, done = [], [], threading.Event()
        self._q.put((fn, result, error, done))
        return result, error, done

    @property
    def alive(self):
        return self._thread.is_alive()


_GUARD_POOL = {"worker": None}


def _take_guard_worker():
    w, _GUARD_POOL["worker"] = _GUARD_POOL["worker"], None
    if w is None or not w.alive:
        w = _GuardWorker()
    return w


def _return_guard_worker(w):
    if _GUARD_POOL["worker"] is None and w.alive:
        _GUARD_POOL["worker"] = w


def guard_collective(fn, detector, poll=0.05, timeout=None,
                     label="collective"):
    """Run ``fn()`` (a collective-bearing callable) on a worker thread
    while polling ``detector`` — the survivors' escape hatch from a
    wedged psum.

    * peer declared dead while waiting → :class:`PeerDeadError` raised
      HERE, the worker thread abandoned (daemon: a native call blocked
      on a dead peer's socket cannot be cancelled, only orphaned);
    * ``fn`` raises (gloo surfaces a connection-reset) → re-checked
      against the detector: a confirmed death raises
      :class:`PeerDeadError` (chained), anything else re-raises as-is;
    * ``timeout`` seconds pass with every peer nominally alive →
      :class:`CollectiveTimeout` (None = wait for the detector alone).

    Returns ``fn()``'s result on the happy path.  The fast path costs
    one Event wait per poll interval; use at step granularity, not
    per-op.
    """
    worker = _take_guard_worker()
    result, error, done = worker.submit(fn)
    t0 = time.monotonic()
    while not done.wait(poll):
        dead = detector.dead_peers() if detector is not None else []
        if dead:
            # abandon: the worker (wedged in a native call against a
            # corpse) is NOT returned to the pool
            try:
                from .. import telemetry

                telemetry.heal("collective_abandon", detail=label,
                               peers=list(dead))
            except Exception:
                pass
            raise PeerDeadError(
                dead, f"abandoned wedged {label} (worker thread "
                "orphaned)")
        if timeout is not None and time.monotonic() - t0 > timeout:
            raise CollectiveTimeout(
                f"guarded {label} exceeded {timeout:.1f}s with all "
                "peers alive")
    _return_guard_worker(worker)
    if error:
        exc = error[0]
        if detector is not None:
            # a backend error (gloo connection-reset) usually BEATS
            # the liveness verdict by milliseconds: give the detector
            # a short confirmation window before deciding this was a
            # transient failure worth re-raising as-is.  The pid/
            # zombie probe confirms a same-host death on the first
            # poll; a genuine transient is delayed by at most ~1 s.
            t_err = time.monotonic()
            grace = min(max(detector.timeout, poll), 1.0)
            while True:
                dead = detector.dead_peers()
                if dead:
                    raise PeerDeadError(
                        dead,
                        f"{label} failed under a dead peer: {exc!r}"
                    ) from exc
                if time.monotonic() - t_err > grace:
                    break
                time.sleep(poll)
        raise exc
    return result[0]


# ------------------------------------------------ emergency checkpoint
# flushers that write the freshest host-side snapshot WITHOUT any
# collective — registered by CheckpointManager async writers and by
# fit's snapshot plumbing; fired by the failure detector's death path
# and the watchdog's abort escalation
_EMERGENCY = []
_EMERGENCY_LOCK = threading.Lock()


def register_emergency(fn):
    """Register ``fn(reason) -> path_or_None`` to run when an
    emergency checkpoint is needed (peer death, watchdog abort).
    Returns ``fn``; idempotent."""
    with _EMERGENCY_LOCK:
        if fn not in _EMERGENCY:
            _EMERGENCY.append(fn)
    return fn


def unregister_emergency(fn):
    with _EMERGENCY_LOCK:
        if fn in _EMERGENCY:
            _EMERGENCY.remove(fn)


def fire_emergency(reason):
    """Run every registered emergency flusher (exceptions swallowed —
    the healing exit must proceed even with a broken flusher); returns
    the paths written."""
    with _EMERGENCY_LOCK:
        hooks = list(_EMERGENCY)
    paths = []
    for fn in hooks:
        try:
            p = fn(reason)
            if p:
                paths.append(p)
        except Exception:
            pass
    if paths:
        try:
            from .. import telemetry

            telemetry.count("emergency_ckpts")
            telemetry.heal("emergency_ckpt", detail=reason,
                           paths=paths)
        except Exception:
            pass
    return paths


def heal_exit(reason, code=PEER_DEATH_EXIT_CODE):
    """The survivor's exit: emergency checkpoint from the freshest
    snapshot, flight dump, telemetry closed (run_end + final
    counters), then ``os._exit`` — NOT ``sys.exit``, because a
    jax.distributed teardown with a dead peer wedges the interpreter's
    atexit chain forever (measured: the survivor of a SIGKILLed peer
    never reaches the prompt)."""
    fire_emergency(reason)
    try:
        from .. import telemetry

        telemetry.flight_dump(f"heal:{reason}")
        telemetry.heal("heal_exit", detail=reason, code=int(code))
        telemetry.close()
    except Exception:
        pass
    os._exit(int(code))


# ------------------------------------------------------ session arming
_STATE = {"hb": None, "detector": None}


def arm(hb_dir, rank, num_ranks, timeout=None, interval=None):
    """Arm the process-wide healing session: start this rank's
    heartbeat and a failure detector over the peer set.  Module.fit
    polls the armed detector at step boundaries; :func:`poll` is the
    ambient accessor.  Idempotent per (dir, rank)."""
    hb = _STATE["hb"]
    det = _STATE["detector"]
    if hb is not None and det is not None \
            and hb.hb_dir == os.fspath(hb_dir) \
            and hb.rank == int(rank) \
            and det.num_ranks == int(num_ranks) \
            and (timeout is None or det.timeout == float(timeout)):
        return det  # identical world: idempotent.  A CHANGED world
        #             (num_ranks/timeout) re-arms — a detector still
        #             watching the old rank set would miss new peers'
        #             deaths entirely
    disarm()
    if interval is None and timeout is not None:
        # an EXPLICIT timeout must drive the beat cadence too: beating
        # at the env default's timeout/4 while detecting at a shorter
        # explicit timeout would make every fresh rank look stale —
        # systematic false deaths and relaunch churn
        interval = max(0.05, float(timeout) / 4)
    _STATE["hb"] = Heartbeater(hb_dir, rank, interval=interval)
    _STATE["detector"] = FailureDetector(hb_dir, rank, num_ranks,
                                         timeout=timeout)
    return _STATE["detector"]


def arm_from_env():
    """Arm from the environment when configured: ``MXNET_HEARTBEAT_DIR``
    set AND a live elastic context (or MXNET_NUM_PROCESSES) with more
    than one process.  Returns the detector or None — the fit-loop
    call site stays one cheap check when healing is off."""
    from ..config import get_env

    hb_dir = get_env("MXNET_HEARTBEAT_DIR")
    if not hb_dir:
        return _STATE["detector"]
    from . import elastic

    ctx = elastic.context()
    if ctx is not None:
        rank, n = ctx.process_id, ctx.num_processes
    else:
        n = int(get_env("MXNET_NUM_PROCESSES") or 0)
        rank = int(get_env("MXNET_PROCESS_ID"))
    if n <= 1:
        return _STATE["detector"]
    if not 0 <= rank < n:
        # MXNET_PROCESS_ID's registered default is -1 (unresolved):
        # arming with a bogus rank would beat as rank -1 while
        # watching ranks that never beat — every peer (and self)
        # falsely dead within one timeout.  Unresolved identity means
        # healing stays unarmed, loudly.
        import logging

        logging.getLogger("mxnet_tpu").warning(
            "MXNET_HEARTBEAT_DIR set with %d processes but "
            "MXNET_PROCESS_ID=%d is not a valid rank — peer healing "
            "NOT armed", n, rank)
        return _STATE["detector"]
    return arm(hb_dir, rank, n)


def detector():
    """The armed FailureDetector, or None."""
    return _STATE["detector"]


def heartbeater():
    return _STATE["hb"]


def poll(step=None):
    """Step-boundary healing check: renew the beat (with the step
    number) and raise :class:`PeerDeadError` on a declared death.
    No-op (one dict lookup) when healing is unarmed."""
    det = _STATE["detector"]
    if det is None:
        return
    hb = _STATE["hb"]
    if hb is not None:
        hb.beat(step)
    det.check()


def disarm():
    hb, _STATE["hb"] = _STATE["hb"], None
    _STATE["detector"] = None
    if hb is not None:
        hb.close()


def session(hb_dir, rank, num_ranks, timeout=None, interval=None):
    """Context-manager form of :func:`arm`/:func:`disarm`."""
    class _S:
        def __enter__(self_s):
            return arm(hb_dir, rank, num_ranks, timeout=timeout,
                       interval=interval)

        def __exit__(self_s, *exc):
            disarm()
            return False

    return _S()


def relaunch_attempt():
    """Which supervisor relaunch attempt this process is (0 = the
    first launch).  Workers use it to decide whether to re-resolve
    their world from the surviving peers."""
    try:
        return int(os.environ.get("MXNET_HEAL_ATTEMPT", "0"))
    except ValueError:
        return 0


# ----------------------------------------------------------- supervisor
#: child exit statuses the supervisor treats as healable: a survivor's
#: deliberate heal_exit, any signal kill (SIGKILL'd rank, OOM), and
#: the faultsim crash code (a chaos-injected power loss)
def _healable(rc):
    return rc == PEER_DEATH_EXIT_CODE or rc < 0 \
        or rc == faultsim.CRASH_EXIT_CODE


def supervise(cmd, max_relaunch=None, env=None, healable=None):
    """Run ``cmd`` (argv list) under the respawn policy: a healable
    death relaunches it (``MXNET_HEAL_ATTEMPT`` exported, bumped per
    attempt; the ``heal.relaunch`` fault point fires before every
    respawn) up to ``max_relaunch`` times; any other status — success
    included — is final.  Returns the last exit status."""
    import subprocess

    from ..config import get_env

    if max_relaunch is None:
        max_relaunch = int(get_env("MXNET_HEAL_MAX_RELAUNCH"))
    healable = healable if healable is not None else _healable
    base_env = dict(os.environ if env is None else env)
    attempt = 0
    while True:
        run_env = dict(base_env)
        run_env["MXNET_HEAL_ATTEMPT"] = str(attempt)
        # per-relaunch trace stamp: each attempt gets its own child
        # context so tracemerge shows relaunches as distinct subtrees
        from ..telemetry import tracing

        tracing.stamp_env(run_env, run_env.get(tracing.ROLE_ENV)
                          or "worker", rank=attempt)
        rc = subprocess.call(list(cmd), env=run_env)
        if rc == 0 or not healable(rc) or attempt >= int(max_relaunch):
            if rc != 0 and healable(rc):
                try:
                    from .. import telemetry

                    telemetry.heal("relaunch_exhausted", code=rc,
                                   attempt=attempt)
                except Exception:
                    pass
            return rc
        attempt += 1
        try:
            faultsim.inject("heal.relaunch")
        except MXNetError:
            # the inherited spec names a point only the CHILD's
            # subsystem registers (e.g. online.step): it is aimed at
            # the child, which validates the full spec at its own arm
            # time — a typo still fails loudly where the point lives.
            # FaultInjected is not an MXNetError, so an armed
            # heal.relaunch:raise fault still propagates.
            pass
        try:
            from .. import telemetry

            telemetry.count("heal_relaunches")
            telemetry.heal("relaunch", code=rc, attempt=attempt,
                           detail=" ".join(map(str, cmd))[:200])
        except Exception:
            pass


def main(argv=None):
    """``python -m mxnet_tpu.resilience.healing --relaunch -- CMD...``"""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="mxnet_tpu.resilience.healing",
        description="self-healing supervisor: respawn a training "
        "command on healable deaths (peer death, signal kill, "
        "injected crash)")
    ap.add_argument("--relaunch", action="store_true",
                    help="enable the respawn policy (without it the "
                    "command runs exactly once)")
    ap.add_argument("--max-relaunch", type=int, default=None,
                    help="bound on respawns (default "
                    "MXNET_HEAL_MAX_RELAUNCH)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- CMD ARGS... (the training command)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (pass it after --)")
    if not args.relaunch:
        import subprocess

        return subprocess.call(cmd)
    rc = supervise(cmd, max_relaunch=args.max_relaunch)
    return rc


if __name__ == "__main__":  # pragma: no cover — CLI shell
    import sys

    sys.exit(main())
