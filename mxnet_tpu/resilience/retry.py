"""Bounded exponential-backoff-with-jitter retry.

One shared helper for every transient-failure path (the device-feed
producer's H2D attempts and the PS client ops), replacing ad-hoc
try-once-redial-once chains: attempts are bounded, the delay doubles up
to a cap, and jitter decorrelates the retries of W workers hammering
the same recovering shard.
"""
from __future__ import annotations

import random
import time

__all__ = ["retry_call"]


def retry_call(fn, retry_on=(ConnectionError, EOFError, OSError),
               attempts=4, base_delay=0.05, max_delay=2.0, jitter=0.5,
               deadline=None, on_retry=None):
    """Call ``fn()`` until it succeeds, raising the last error after
    ``attempts`` tries or once ``deadline`` (absolute ``time.monotonic``
    value) passes.

    ``on_retry(attempt_no, exc)`` runs between attempts — the PS client
    drops its dead connection there so the next attempt redials.
    Backoff: ``base_delay * 2**k`` capped at ``max_delay``, then
    stretched by up to ``jitter`` (fraction) of itself at random.
    """
    delay = float(base_delay)
    attempts = max(1, int(attempts))
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as exc:
            timed_out = deadline is not None \
                and time.monotonic() >= deadline
            if attempt >= attempts or timed_out:
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            sleep = min(delay, float(max_delay))
            sleep *= 1.0 + jitter * random.random()
            if deadline is not None:
                sleep = min(sleep, max(0.0,
                                       deadline - time.monotonic()))
            time.sleep(sleep)
            delay *= 2.0
    raise AssertionError("unreachable")  # pragma: no cover
