"""Bounded exponential-backoff-with-jitter retry.

One shared helper for every transient-failure path (the device-feed
producer's H2D attempts and the PS client ops), replacing ad-hoc
try-once-redial-once chains: attempts are bounded, the delay doubles up
to a cap, and jitter decorrelates the retries of W workers hammering
the same recovering shard.
"""
from __future__ import annotations

import random
import time

__all__ = ["retry_call"]


def retry_call(fn, retry_on=(ConnectionError, EOFError, OSError),
               attempts=4, base_delay=0.05, max_delay=2.0, jitter=0.5,
               deadline=None, deadline_sec=None, on_retry=None):
    """Call ``fn()`` until it succeeds, raising the last error after
    ``attempts`` tries or once ``deadline`` (absolute ``time.monotonic``
    value) passes.

    ``deadline_sec`` is the relative form: a TOTAL time budget for the
    whole call, stamped at entry.  Attempt counts alone can overshoot
    a caller's deadline once the exponential backoff grows (4 attempts
    at max_delay=2.0 is already ~6 s of sleeping on top of the call
    costs), so callers with an SLA pass their remaining budget here —
    the PS client threads ``MXNET_PS_DEADLINE_SEC`` through — and the
    retry loop gives up (re-raising the last error) as soon as the
    budget is spent, never sleeping past it.  When both forms are
    given the earlier one wins.

    ``on_retry(attempt_no, exc)`` runs between attempts — the PS client
    drops its dead connection there so the next attempt redials.
    Backoff: ``base_delay * 2**k`` capped at ``max_delay``, then
    stretched by up to ``jitter`` (fraction) of itself at random.
    """
    if deadline_sec is not None:
        rel = time.monotonic() + float(deadline_sec)
        deadline = rel if deadline is None else min(deadline, rel)
    delay = float(base_delay)
    attempts = max(1, int(attempts))
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as exc:
            timed_out = deadline is not None \
                and time.monotonic() >= deadline
            if attempt >= attempts or timed_out:
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            sleep = min(delay, float(max_delay))
            sleep *= 1.0 + jitter * random.random()
            if deadline is not None \
                    and time.monotonic() + sleep >= deadline:
                # the budget cannot cover even the backoff: give up
                # NOW — sleeping up to the deadline and then launching
                # one more attempt would overshoot the caller's SLA by
                # a full fn() duration
                raise
            time.sleep(sleep)
            delay *= 2.0
    raise AssertionError("unreachable")  # pragma: no cover
