"""Atomic, versioned, verifiable checkpoints.

The reference's checkpoint story is ``nd.save`` straight onto the final
path (python/mxnet/model.py:394): a crash mid-write leaves a torn
``prefix-NNNN.params`` that ``load_checkpoint`` loads blindly.  This
module is the Check-N-Run-style fix every save path now routes through:

* **Atomic writes** — every payload goes write-to-temp + fsync +
  ``os.replace``; the final path is either its previous content or the
  complete new content, never a torn mix.  An injected/real crash
  mid-write leaves only a stray temp file.
* **Versioned manifests** — each checkpoint carries a JSON manifest
  (epoch, step, batch cursor, per-payload size + CRC32, host+device RNG
  state, autotune winners-file hash) and a ``prefix-latest.json``
  pointer written LAST, so "the latest checkpoint" is itself an atomic
  concept.
* **Verification + fallback** — :meth:`CheckpointManager.verify`
  detects truncated/corrupt payloads by size+CRC; ``load()`` /
  ``latest_epoch()`` fall back to the newest version that verifies.
* **Retention** — ``keep_n`` prunes old versions after each save
  (``None`` keeps everything — the legacy ``do_checkpoint`` behavior).

Layout stays legacy-compatible: ``prefix-symbol.json`` +
``prefix-NNNN.params`` (+ ``prefix-NNNN.states``) are exactly the
reference files, so old ``load_checkpoint`` callers keep working; the
manifest and pointer are additive.
"""
from __future__ import annotations

import hashlib
import json
import os
import queue
import threading
import time
import zlib

import numpy as onp

from ..base import MXNetError
from . import faultsim

__all__ = ["CheckpointManager", "atomic_write_bytes", "capture_rng",
           "restore_rng"]


def atomic_write_bytes(path, data, inject_point="ckpt.write"):
    """Write ``data`` to ``path`` atomically: temp file in the same
    directory, fsync, then rename over the target (plus a directory
    fsync so the rename itself is durable).

    The fault-injection point fires MID-payload, so an armed
    ``ckpt.write:crash`` leaves a truncated *temp* file and the final
    path untouched — exactly the torn-write scenario the old direct
    ``nd.save`` could not survive.
    """
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # pid AND thread id: the async snapshot writer and an emergency
    # flush may race toward the same target — distinct temp files keep
    # both writes atomic (the loser's rename is a benign overwrite of
    # identical content)
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}"
           f".{threading.get_ident()}")
    try:
        with open(tmp, "wb") as f:
            half = len(data) // 2
            f.write(data[:half])
            if inject_point:
                faultsim.inject(inject_point)
            f.write(data[half:])
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platforms/filesystems without directory fsync


def capture_rng():
    """Snapshot host (numpy) and device (mxnet_tpu._rng key) RNG state
    as JSON-serializable data, so a resumed run continues the exact
    random stream of the interrupted one.

    The 624-word Mersenne state rides as base64 of its raw bytes: the
    async snapshot cadence calls this at every capture, and a
    2500-element Python list was the single most expensive item on
    that step-boundary path (``restore_rng`` accepts both this and
    the legacy list form, so old manifests keep loading)."""
    import base64

    st = onp.random.get_state()
    key = onp.asarray(st[1], onp.uint32)
    state = {"numpy": [st[0],
                       {"b64": base64.b64encode(
                           key.tobytes()).decode("ascii")},
                       int(st[2]), int(st[3]), float(st[4])],
             "device": None}
    try:
        import jax

        from .. import _rng

        if _rng._S.key is not None:
            state["device"] = onp.asarray(
                jax.random.key_data(_rng._S.key),
                onp.uint32).tolist()
    except Exception:
        pass  # key API absent or backend not initialized: host-only
    return state


def restore_rng(state):
    """Restore a :func:`capture_rng` snapshot (missing parts no-op).
    Accepts both the base64 key form and the legacy integer-list form
    (pre-round-16 manifests)."""
    if not state:
        return
    np_st = state.get("numpy")
    if np_st:
        key = np_st[1]
        if isinstance(key, dict):
            import base64

            key = onp.frombuffer(
                base64.b64decode(key["b64"]), onp.uint32)
        onp.random.set_state((np_st[0],
                              onp.asarray(key, onp.uint32),
                              int(np_st[2]), int(np_st[3]),
                              float(np_st[4])))
    dev = state.get("device")
    if dev is not None:
        try:
            import jax
            import jax.numpy as jnp

            from .. import _rng

            _rng._S.key = jax.random.wrap_key_data(
                jnp.asarray(dev, jnp.uint32))
        except Exception:
            pass


_AT_HASH_CACHE = {"key": None, "hash": None}


def _autotune_hash():
    """SHA-256 of the persisted autotune winners file, recorded so a
    resume can tell whether it is replaying under the same variant
    choices the checkpointed run trained with.  Memoized by
    (path, mtime, size): the async snapshot cadence calls this per
    capture and the winners file changes rarely — a stat beats a
    read+hash on the step-boundary path."""
    try:
        from .. import autotune

        p = autotune.cache_path()
        st = os.stat(p)
        key = (p, st.st_mtime_ns, st.st_size)
        if _AT_HASH_CACHE["key"] != key:
            with open(p, "rb") as f:
                _AT_HASH_CACHE["hash"] = \
                    hashlib.sha256(f.read()).hexdigest()
            _AT_HASH_CACHE["key"] = key
        return _AT_HASH_CACHE["hash"]
    except Exception:
        return None


def _crc(blob):
    return zlib.crc32(blob) & 0xFFFFFFFF


def _as_nd(v):
    from .. import ndarray as nd

    data = v._data if isinstance(v, nd.NDArray) else v
    if hasattr(data, "sharding") and hasattr(data, "devices") \
            and len(data.devices()) > 1:
        # mesh-backed array (replicated module weights, or a ZeRO
        # bucket shard under optimizer_sharding="ps"): GATHER to one
        # host copy here — via host_gather, which also handles arrays
        # spanning PROCESSES on a real multi-host mesh — so what lands
        # on disk is the legacy world-size-agnostic single-array
        # layout and never aliases a device buffer a donating step may
        # consume mid-save
        from .elastic import host_gather

        return nd.array(host_gather(data))
    return v if isinstance(v, nd.NDArray) else nd.array(onp.asarray(v))


def stage3_save_params(plan, params):
    """ZeRO stage-3 -> legacy named ``arg_params`` for :meth:`save`.

    Under stage 3 the live params pytree is ``{"_bucket<i>": flat
    padded bucket}`` sharded over the data axis — no single host holds
    a whole parameter.  Each bucket gathers to one host copy (through
    ``host_gather``, the only collective on this path — on a real
    multi-host mesh every peer must still be alive) and re-splits into
    the named tree, so the ``.params`` file on disk stays
    bit-interchangeable with replicated and stage-1/2 runs."""
    from ..parallel.zero import gather_stage3_params, stage3_param_keys
    from .elastic import host_gather

    gathered = {k: host_gather(
        v._data if hasattr(v, "_data") else v)
        for k, v in params.items() if k in set(stage3_param_keys(plan))}
    return gather_stage3_params(plan, gathered)


def stage3_load_params(plan, arg_params, mesh=None, data_axis="data"):
    """Inverse of :func:`stage3_save_params`: re-shard a loaded named
    ``arg_params`` dict into the stage-3 flat-bucket layout (placed
    over ``mesh`` when given) — the resume path of a stage-tagged
    checkpoint.  The caller must verify the manifest topology first
    (``reshard_verdict``): a plan-fingerprint mismatch means these
    buckets would misread, not misload."""
    from ..parallel.zero import shard_stage3_params

    named = {k: (v._data if hasattr(v, "_data") else onp.asarray(v))
             for k, v in arg_params.items()}
    return shard_stage3_params(plan, named, mesh=mesh,
                               data_axis=data_axis)


def _split_params(save_dict):
    """Split a loaded ``arg:``/``aux:``-keyed dict (the reference
    .params convention) into (arg_params, aux_params)."""
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


class CheckpointManager:
    """Owner of one checkpoint series under ``prefix``.

    Files per version ``NNNN`` (all written atomically, manifest after
    payloads, ``latest`` pointer last):

    * ``prefix-NNNN.params``        — ``arg:``/``aux:`` blobs, the
      reference binary format (``load_checkpoint`` compatible)
    * ``prefix-NNNN.states``        — pickled optimizer state (optional)
    * ``prefix-NNNN.manifest.json`` — epoch/step/cursor, per-payload
      size+CRC32, RNG snapshot, autotune winners hash
    * ``prefix-symbol.json``        — the network (shared across versions)
    * ``prefix-latest.json``        — pointer to the newest version

    Sharded-optimizer runs (``optimizer_sharding="ps"``): the save
    path GATHERS — mesh-backed params gather here in ``_as_nd`` and
    the ``ShardedBucketUpdater`` gathers its bucket shards into the
    legacy per-param states pickle before it reaches ``save`` — so the
    on-disk layout is identical to a replicated run's; loading into a
    sharded run RE-SHARDS (``ShardedBucketUpdater.set_states``), which
    is why ``.states`` files move freely between the two modes.
    """

    MANIFEST_FORMAT = 1

    def __init__(self, prefix, keep_n=None):
        self.prefix = os.fspath(prefix)
        self.keep_n = keep_n
        self._vlock = threading.Lock()
        self._reserved = 0        # highest version handed out in-process
        self._write_lock = threading.Lock()  # serializes version writes
        self._async = None        # lazy _AsyncWriter
        self._freshest = None     # newest captured snapshot (host-side)
        self._written = set()     # versions already durably written
        self._good_cache = set()  # versions that verified (this process)

    # ------------------------------------------------------------ paths
    def params_path(self, epoch):
        return f"{self.prefix}-{int(epoch):04d}.params"

    def states_path(self, epoch):
        return f"{self.prefix}-{int(epoch):04d}.states"

    def manifest_path(self, epoch):
        return f"{self.prefix}-{int(epoch):04d}.manifest.json"

    def symbol_path(self):
        return f"{self.prefix}-symbol.json"

    def latest_path(self):
        return f"{self.prefix}-latest.json"

    def _dir(self):
        return os.path.dirname(os.path.abspath(self.prefix)) or "."

    # ------------------------------------------------------------- save
    def save(self, version, symbol=None, symbol_json=None,
             arg_params=None, aux_params=None, optimizer_states=None,
             step=None, batch_cursor=0, extra=None, epoch=None,
             topology=None, lock_timeout=None):
        """Write one atomic checkpoint version; returns its manifest.

        ``version`` names the files (``prefix-NNNN.*``); ``epoch`` is
        the training epoch recorded in the manifest and defaults to
        the version — they coincide for clean epoch-boundary saves,
        and diverge when fit's mid-epoch drain allocates a fresh
        version id to avoid rewriting an existing one in place.
        ``batch_cursor`` records how many batches of that epoch were
        already consumed (0 = a clean epoch boundary) — the resume
        cursor for mid-epoch preemption drains.

        ``topology`` (``resilience.elastic.topology_block``) stamps
        the world the checkpoint was written FROM — world size, mesh
        shape, optimizer-sharding mode, bucket-plan fingerprint,
        global batch — so a resume at a different world size can
        detect the mismatch and re-plan/re-shard instead of dying,
        while a same-topology resume provably skips the reshard.
        """
        cap = self._capture(version, symbol=symbol,
                            symbol_json=symbol_json,
                            arg_params=arg_params,
                            aux_params=aux_params,
                            optimizer_states=optimizer_states,
                            step=step, batch_cursor=batch_cursor,
                            extra=extra, epoch=epoch,
                            topology=topology)
        return self._write_version(cap, lock_timeout=lock_timeout)

    # -------------------------------------------------- capture / write
    def _capture(self, version, symbol=None, symbol_json=None,
                 arg_params=None, aux_params=None,
                 optimizer_states=None, step=None, batch_cursor=0,
                 extra=None, epoch=None, topology=None):
        """Snapshot everything a checkpoint needs onto the HOST, now:
        the device→host copy of every param (``_as_nd`` gathers
        mesh-backed arrays — a collective, so this must run at a step
        boundary while every peer is alive), the RNG state and the
        autotune hash.  The returned dict is self-contained: writing
        it later (async writer thread, emergency flush) touches no
        device and needs no peer."""
        version = int(version)
        with self._vlock:
            self._reserved = max(self._reserved, version)
        save_dict = {f"arg:{k}": _as_nd(v) for k, v in
                     (arg_params or {}).items()}
        save_dict.update({f"aux:{k}": _as_nd(v) for k, v in
                          (aux_params or {}).items()})
        if symbol_json is None and symbol is not None:
            symbol_json = symbol.tojson()
        return {
            "version": version,
            "epoch": version if epoch is None else int(epoch),
            "save_dict": save_dict,
            "optimizer_states": optimizer_states,
            "symbol_json": symbol_json,
            "step": step,
            "batch_cursor": int(batch_cursor),
            "rng": capture_rng(),
            "autotune_sha256": _autotune_hash(),
            "topology": topology,
            "extra": extra or {},
        }

    def _write_version(self, cap, inject_point="ckpt.write",
                       telemetry_extra=None, skip_if_written=False,
                       lock_timeout=None):
        """Serialize + atomically write one captured snapshot: every
        payload write-to-temp+fsync+rename, manifest after payloads,
        ``latest`` pointer LAST — a crash anywhere leaves the previous
        complete version as ``latest``.  Serialized against concurrent
        writers (async thread vs emergency flush vs sync save).
        ``skip_if_written`` (the async/emergency paths, which race
        toward the same allocated version) returns None instead of
        rewriting a version this process already made durable; the
        sync ``save()`` keeps its legacy rewrite-in-place semantics.
        ``lock_timeout`` bounds the wait for the writer lock (the
        emergency/abort paths: when the wedge IS a hung write holding
        the lock, blocking here would stop the abort from ever
        reaching its ``os._exit``) — on timeout, None."""
        from .. import ndarray as nd

        t_save0 = time.perf_counter()
        version = cap["version"]
        if lock_timeout is None:
            self._write_lock.acquire()
        elif not self._write_lock.acquire(timeout=float(lock_timeout)):
            return None  # the lock holder is wedged: do not join it
        try:
            if skip_if_written and version in self._written:
                return None  # already durably written (emergency won)
            files = {}
            payload = nd.save_buffer(cap["save_dict"])
            ppath = self.params_path(version)
            atomic_write_bytes(ppath, payload,
                               inject_point=inject_point)
            files[os.path.basename(ppath)] = {
                "bytes": len(payload), "crc32": _crc(payload)}
            states = cap.get("optimizer_states")
            if states is not None:
                spath = self.states_path(version)
                atomic_write_bytes(spath, states,
                                   inject_point=inject_point)
                files[os.path.basename(spath)] = {
                    "bytes": len(states), "crc32": _crc(states)}
            sj = cap.get("symbol_json")
            if sj is not None:
                # the symbol file is SHARED across versions: skip the
                # rewrite when this manager already wrote identical
                # content (the cadence-snapshot path would otherwise
                # re-write an unchanged multi-MB graph per snapshot)
                sj_crc = _crc(sj.encode())
                if getattr(self, "_symbol_crc", None) != sj_crc:
                    atomic_write_bytes(self.symbol_path(),
                                       sj.encode(),
                                       inject_point=inject_point)
                    self._symbol_crc = sj_crc
            manifest = {
                "format": self.MANIFEST_FORMAT,
                "version": version,
                "epoch": cap["epoch"],
                "step": cap.get("step"),
                "batch_cursor": int(cap.get("batch_cursor", 0)),
                "files": files,
                "rng": cap.get("rng"),
                "autotune_sha256": cap.get("autotune_sha256"),
                "topology": cap.get("topology"),
                "time": time.time(),
                "extra": cap.get("extra") or {},
            }
            atomic_write_bytes(self.manifest_path(version),
                               json.dumps(manifest, indent=1).encode(),
                               inject_point=inject_point)
            # the pointer goes LAST: a crash anywhere above leaves
            # `latest` naming the previous complete version.  On the
            # ASYNC/emergency paths it only ever moves FORWARD (a
            # queued snapshot landing after a newer drain save must
            # not point resumes back at the older version); a sync
            # save() keeps the legacy rule — the pointer follows the
            # last explicit save, lower version number or not
            cur = -1
            if skip_if_written:
                try:
                    with open(self.latest_path(), "rb") as f:
                        cur = int(json.loads(f.read())["epoch"])
                except (OSError, ValueError, KeyError, TypeError):
                    pass  # unreadable/corrupt pointer: overwrite it
            if version >= cur:
                atomic_write_bytes(
                    self.latest_path(),
                    json.dumps({"epoch": version,
                                "manifest": os.path.basename(
                                    self.manifest_path(version))}
                               ).encode(),
                    inject_point=inject_point)
            self._written.add(version)
            # just written from in-memory blobs whose CRCs the manifest
            # records: good by construction for this process's
            # retention decisions
            self._good_cache.add(version)
            self._apply_retention()
        finally:
            self._write_lock.release()
        from .. import telemetry

        telemetry.checkpoint_event(
            self.prefix, version, time.perf_counter() - t_save0,
            sum(f["bytes"] for f in files.values()),
            **(telemetry_extra or {}))
        return manifest

    def allocate_version(self, min_version=1):
        """A fresh monotonic version id: past everything on disk AND
        everything captured-but-unwritten in this process (the async
        queue), so sync saves, async snapshots and emergency flushes
        never collide.  ``min_version`` lets fit keep the legacy
        version==epoch naming for the first clean save."""
        with self._vlock:
            eps = self.epochs()
            v = max((eps[-1] + 1) if eps else 1, self._reserved + 1,
                    int(min_version))
            self._reserved = v
            return v

    # -------------------------------------------------- async snapshots
    def save_async(self, version=None, symbol=None, symbol_json=None,
                   arg_params=None, aux_params=None,
                   optimizer_states=None, step=None, batch_cursor=0,
                   extra=None, epoch=None, topology=None,
                   queue_depth=2):
        """Asynchronous snapshot checkpoint: capture NOW (device→host
        at the caller's step boundary), write LATER (serialization +
        atomic writes on a background thread), so the training step
        never waits on the disk.

        * the bounded queue (``queue_depth``) back-pressures: when the
          disk cannot keep up, the CALLER blocks on the next
          ``save_async`` instead of snapshots accumulating unboundedly
          in host memory;
        * the freshest capture is retained in memory and registered as
          the EMERGENCY checkpoint source (:mod:`.healing`): a peer
          death or watchdog abort flushes it synchronously — no
          collective needed, the gather already happened while the
          mesh was whole;
        * the ``ckpt.async`` fault point fires mid-payload inside the
          writer thread: an armed ``crash`` proves a mid-write death
          leaves ``latest`` == previous-good with no torn final file;
        * each completed write bumps the ``ckpt_async_writes`` counter
          and emits the standard ``checkpoint`` record with
          ``async=True``.

        Returns the allocated version id immediately.
        """
        if version is None:
            version = self.allocate_version()
        cap = self._capture(version, symbol=symbol,
                            symbol_json=symbol_json,
                            arg_params=arg_params,
                            aux_params=aux_params,
                            optimizer_states=optimizer_states,
                            step=step, batch_cursor=batch_cursor,
                            extra=extra, epoch=epoch,
                            topology=topology)
        self._freshest = cap
        if self._async is None:
            self._async = _AsyncWriter(self, depth=int(queue_depth))
            from . import healing

            healing.register_emergency(self._emergency_hook)
        self._async.submit(cap)
        return int(version)

    def wait_async(self, timeout=None):
        """Block until every queued snapshot is durably written (the
        drain/exit path: a final sync save must not overtake a queued
        async one in the version order a resume trusts)."""
        if self._async is not None:
            return self._async.drain(timeout=timeout)
        return True

    def close_async(self, timeout=None):
        """Drain and stop the writer thread; unregisters the emergency
        hook.  Idempotent."""
        wr, self._async = self._async, None
        if wr is None:
            return True
        from . import healing

        healing.unregister_emergency(self._emergency_hook)
        return wr.close(timeout=timeout)

    def flush_emergency(self, reason="emergency", lock_timeout=10.0):
        """Synchronously write the freshest captured snapshot if it is
        not yet on disk — the failure detector's death path and the
        watchdog's abort escalation call this (directly or through
        ``healing.fire_emergency``).  Fault injection is DISABLED for
        this write, and the writer lock is acquired with a TIMEOUT:
        when the wedge being escaped is itself a hung checkpoint write
        holding the lock, the emergency must give up and let the abort
        reach its ``os._exit`` instead of joining the deadlock.
        Returns the manifest path written, or None when the freshest
        snapshot is already durable (or unreachable)."""
        cap = self._freshest
        if cap is None:
            return None
        if cap["version"] in self._written:
            return None
        cap = dict(cap)
        cap.setdefault("extra", {})
        cap["extra"] = dict(cap["extra"], emergency=reason)
        man = self._write_version(cap, inject_point=None,
                                  telemetry_extra={"emergency": reason},
                                  skip_if_written=True,
                                  lock_timeout=lock_timeout)
        if man is None:
            return None
        return self.manifest_path(cap["version"])

    def _emergency_hook(self, reason):
        return self.flush_emergency(reason)

    # --------------------------------------------------------- retention
    def _verified_good(self, e):
        """verify() with a positive memo: a version this process wrote
        or already verified is trusted without re-reading its payloads
        on every retention sweep (rot after a positive verdict is the
        accepted trade — retention is belt-and-braces, fsck re-reads
        everything)."""
        if e in self._good_cache:
            return True
        if self.verify(e):
            self._good_cache.add(e)
            return True
        return False

    def _apply_retention(self):
        """keep_n retention that can never garbage-collect the
        recovery chain: the newest ``keep_n`` VERIFIED-GOOD versions
        are kept (torn versions do not count against the window), and
        only versions strictly older than the oldest kept good one are
        pruned.  With every version healthy this is exactly the old
        count-based prune; with the newest versions torn (foreign
        truncation, bit rot, a lying fsync) the last good generations
        survive — the count-based prune deleted the newest good
        version while keeping its torn juniors."""
        if not self.keep_n or int(self.keep_n) <= 0:
            return
        keep_n = int(self.keep_n)
        eps = self.epochs()
        if len(eps) <= keep_n:
            return
        # NEWEST-first with early stop: verification walks down only
        # until keep_n good versions are found.  A save through this
        # manager just seeded its own version into the good-cache, so
        # the steady state re-reads at most keep_n-1 older payloads —
        # and only on the first sweep of a freshly constructed
        # manager (later sweeps hit the cache for everything kept).
        good_found = 0
        floor = None
        for e in reversed(eps):
            if self._verified_good(e):
                good_found += 1
                if good_found >= keep_n:
                    floor = e
                    break
        if good_found == 0:
            return  # nothing verifies: delete NOTHING — any file may
            #         be the operator's last forensic straw
        if floor is None:
            return  # fewer than keep_n good versions exist: keep all
        for e in eps:
            if e >= floor:
                continue
            for p in (self.params_path(e), self.states_path(e),
                      self.manifest_path(e)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            self._good_cache.discard(e)

    # ----------------------------------------------------------- lookup
    def epochs(self):
        """All versions on disk (ascending), from their manifests."""
        base = os.path.basename(self.prefix)
        out = []
        try:
            names = os.listdir(self._dir())
        except OSError:
            return out
        suffix = ".manifest.json"
        for n in names:
            if n.startswith(base + "-") and n.endswith(suffix):
                num = n[len(base) + 1:-len(suffix)]
                if num.isdigit():
                    out.append(int(num))
        return sorted(out)

    def _read_manifest(self, epoch):
        with open(self.manifest_path(epoch), "rb") as f:
            return json.loads(f.read().decode())

    def has_manifest(self, epoch):
        return os.path.exists(self.manifest_path(epoch))

    def _read_verified(self, epoch):
        """Manifest + every payload in ONE read each, CRC-checked as
        read.  The recovery path (load) decodes from these buffers
        directly, so verification never doubles the disk I/O of a
        multi-GB resume."""
        man = self._read_manifest(epoch)
        blobs = {}
        for fname, meta in man["files"].items():
            fp = os.path.join(self._dir(), fname)
            with open(fp, "rb") as f:
                blob = f.read()
            if len(blob) != meta.get("bytes") \
                    or _crc(blob) != meta.get("crc32"):
                raise MXNetError(
                    f"checkpoint payload {fp!r} failed verification "
                    "(truncated or corrupt)")
            blobs[fname] = blob
        return man, blobs

    def verify(self, epoch):
        """True iff the manifest parses and every payload matches its
        recorded size and CRC32 — catches truncation, bit rot, and
        torn non-atomic writes from foreign tools."""
        return self.verify_detail(epoch) is None

    def verify_detail(self, epoch):
        """None when the version verifies, else a one-line problem
        NAMING the offending file — what ``tools/ckpt_fsck.py`` prints
        so an operator knows which artifact is torn, not just which
        version."""
        try:
            self._read_verified(epoch)
            return None
        except MXNetError as e:
            return str(e)
        except OSError as e:
            return (f"checkpoint manifest/payload unreadable: "
                    f"{getattr(e, 'filename', None) or e}")
        except (ValueError, KeyError) as e:
            return (f"checkpoint manifest {self.manifest_path(epoch)!r}"
                    f" malformed ({type(e).__name__}: {e})")

    def _latest_candidates(self):
        """Version numbers to try, newest-first: the ``latest``
        pointer's target, then every other on-disk version."""
        candidates = []
        try:
            with open(self.latest_path(), "rb") as f:
                candidates.append(int(json.loads(f.read())["epoch"]))
        except (OSError, ValueError, KeyError, TypeError):
            pass  # unreadable/corrupt pointer (non-numeric epoch
            #       included): fall back through on-disk versions
        for e in reversed(self.epochs()):
            if e not in candidates:
                candidates.append(e)
        return candidates

    def latest_epoch(self):
        """Newest version that VERIFIES, or None.

        The ``latest`` pointer is consulted first; a corrupt or
        missing candidate falls back through older versions (newest
        first) — the previous-good-version guarantee.
        """
        for e in self._latest_candidates():
            if self.verify(e):
                return e
        return None

    # ------------------------------------------------------------- load
    def load(self, epoch=None, ctx=None):
        """Load a verified checkpoint.

        ``epoch=None`` loads the newest version that verifies (falling
        back past corrupt ones); an explicit version number raises
        :class:`MXNetError` when that version fails verification —
        detection, not silent substitution, for a pinned request.

        Returns a dict with ``version`` (the file id), ``epoch`` (the
        training epoch from the manifest — diverges from the version
        after mid-epoch drains), ``step``, ``batch_cursor``,
        ``arg_params``, ``aux_params`` (NDArray dicts),
        ``optimizer_states`` (bytes or None), ``rng``, ``topology``
        (the world stamp, or None for pre-elastic files) and
        ``extra``.
        """
        from .. import ndarray as nd

        man, blobs = {}, {}
        if epoch is None:
            # newest-good fallback, ONE read per candidate: the blobs
            # that verified are the blobs that get decoded
            t_load0 = time.perf_counter()
            skipped = []
            for cand in self._latest_candidates():
                try:
                    man, blobs = self._read_verified(cand)
                    epoch = cand
                    break
                except (OSError, ValueError, KeyError, MXNetError):
                    skipped.append(int(cand))
                    continue
            if epoch is None:
                raise MXNetError(
                    f"no verifiable checkpoint under {self.prefix!r}")
            if skipped:
                # the recovery was SILENT before: an operator whose
                # newest checkpoint is rotting learned it only when the
                # loss curve jumped back.  Emit a schema-valid
                # checkpoint record naming the skipped bad versions and
                # bump the ckpt_fallbacks counter (exported to the
                # Prometheus textfile) so the rot pages someone.
                from .. import telemetry

                telemetry.count("ckpt_fallbacks")
                telemetry.checkpoint_event(
                    self.prefix, epoch,
                    time.perf_counter() - t_load0,
                    sum(len(b) for b in blobs.values()),
                    reason="fallback", skipped_versions=skipped)
        else:
            epoch = int(epoch)
            if self.has_manifest(epoch):
                try:
                    man, blobs = self._read_verified(epoch)
                except MXNetError as e:
                    raise MXNetError(
                        f"checkpoint {self.params_path(epoch)!r} "
                        "failed verification (truncated or corrupt "
                        "payload); load(epoch=None) falls back to the "
                        "last good version") from e
            # manifest-less versions (pre-atomic-writer files) load
            # blind, the legacy behavior

        pname = os.path.basename(self.params_path(epoch))
        if pname in blobs:
            save_dict = nd.load_buffer(blobs[pname], ctx=ctx)
        else:
            save_dict = nd.load(self.params_path(epoch), ctx=ctx)
        arg_params, aux_params = _split_params(save_dict)
        sname = os.path.basename(self.states_path(epoch))
        states = blobs.get(sname)
        if states is None and os.path.exists(self.states_path(epoch)):
            with open(self.states_path(epoch), "rb") as f:
                states = f.read()
        return {
            "version": int(epoch),
            "epoch": int(man.get("epoch", epoch)),
            "step": man.get("step"),
            "batch_cursor": int(man.get("batch_cursor", 0)),
            "arg_params": arg_params,
            "aux_params": aux_params,
            "optimizer_states": states,
            "rng": man.get("rng"),
            "autotune_sha256": man.get("autotune_sha256"),
            "topology": man.get("topology"),
            "extra": man.get("extra", {}),
        }

    def load_params_dict(self, version, ctx=None):
        """One version's ``.params`` dict in a SINGLE read: with a
        manifest the payload is CRC-verified and decoded from the same
        buffer (raises on mismatch — detection for a pinned version);
        manifest-less files load blind, the legacy behavior."""
        from .. import ndarray as nd

        version = int(version)
        if self.has_manifest(version):
            try:
                _, blobs = self._read_verified(version)
            except (OSError, ValueError, KeyError, MXNetError) as e:
                raise MXNetError(
                    f"checkpoint {self.params_path(version)!r} failed "
                    "verification (truncated or corrupt payload); "
                    "CheckpointManager.load() falls back to the last "
                    "good version") from e
            pname = os.path.basename(self.params_path(version))
            if pname in blobs:
                return nd.load_buffer(blobs[pname], ctx=ctx)
        return nd.load(self.params_path(version), ctx=ctx)


class _AsyncWriter:
    """The snapshot-checkpoint background writer: one daemon thread
    draining a BOUNDED queue of captured snapshots.

    The bound is the back-pressure contract: a disk slower than the
    snapshot cadence blocks the producer (the training loop's
    ``save_async``) on ``queue.put`` instead of accumulating host
    copies without limit.  The ``ckpt.async`` fault point fires
    mid-payload inside every write this thread performs — an armed
    ``crash`` is the power-loss-during-async-write drill.
    """

    def __init__(self, mgr, depth=2):
        self.mgr = mgr
        self._q = queue.Queue(maxsize=max(1, int(depth)))
        self._cv = threading.Condition()
        self._submitted = 0
        self._completed = 0
        self._stop = False
        self._errors = []
        self._thread = threading.Thread(
            target=self._run, name="mxnet_tpu-ckpt-async", daemon=True)
        self._thread.start()

    def submit(self, cap):
        with self._cv:
            self._submitted += 1
        self._q.put(cap)  # blocks when the disk is behind: backpressure

    def _run(self):
        while True:
            cap = self._q.get()
            if cap is None:
                return
            try:
                faultsim.inject("ckpt.async")
                man = self.mgr._write_version(
                    cap, inject_point="ckpt.async",
                    telemetry_extra={"async": True},
                    skip_if_written=True)
                if man is not None:
                    from .. import telemetry

                    telemetry.count("ckpt_async_writes")
            except Exception as e:  # a broken disk must not kill the
                # writer thread — but it must not be SILENT either:
                # the operator believes batches-fresh recovery points
                # exist, so every failed snapshot is logged, counted,
                # and recorded (the emergency path will hit the same
                # disk, with prior warning instead of none)
                self._errors.append(e)
                import logging

                logging.getLogger("mxnet_tpu").warning(
                    "async snapshot write failed (version %s): %r",
                    cap.get("version"), e)
                try:
                    from .. import telemetry

                    telemetry.count("ckpt_async_errors")
                    telemetry.event("ckpt_async_error",
                                    version=cap.get("version"),
                                    error=repr(e))
                except Exception:
                    pass
            finally:
                with self._cv:
                    self._completed += 1
                    self._cv.notify_all()

    def drain(self, timeout=None):
        """True once every snapshot submitted so far is written (or
        failed into ``errors``)."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._completed >= self._submitted,
                timeout=timeout)

    def close(self, timeout=None):
        timeout = 10.0 if timeout is None else float(timeout)
        if not self._stop:
            self._stop = True
            try:
                # the sentinel must NOT block forever: with the writer
                # wedged on a bad disk the bounded queue stays full —
                # close() (fit's finally) abandons the daemon thread
                # instead of joining the hang
                self._q.put(None, timeout=timeout)
            except queue.Full:
                pass
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    @property
    def errors(self):
        return list(self._errors)
