"""Atomic, versioned, verifiable checkpoints.

The reference's checkpoint story is ``nd.save`` straight onto the final
path (python/mxnet/model.py:394): a crash mid-write leaves a torn
``prefix-NNNN.params`` that ``load_checkpoint`` loads blindly.  This
module is the Check-N-Run-style fix every save path now routes through:

* **Atomic writes** — every payload goes write-to-temp + fsync +
  ``os.replace``; the final path is either its previous content or the
  complete new content, never a torn mix.  An injected/real crash
  mid-write leaves only a stray temp file.
* **Versioned manifests** — each checkpoint carries a JSON manifest
  (epoch, step, batch cursor, per-payload size + CRC32, host+device RNG
  state, autotune winners-file hash) and a ``prefix-latest.json``
  pointer written LAST, so "the latest checkpoint" is itself an atomic
  concept.
* **Verification + fallback** — :meth:`CheckpointManager.verify`
  detects truncated/corrupt payloads by size+CRC; ``load()`` /
  ``latest_epoch()`` fall back to the newest version that verifies.
* **Retention** — ``keep_n`` prunes old versions after each save
  (``None`` keeps everything — the legacy ``do_checkpoint`` behavior).

Layout stays legacy-compatible: ``prefix-symbol.json`` +
``prefix-NNNN.params`` (+ ``prefix-NNNN.states``) are exactly the
reference files, so old ``load_checkpoint`` callers keep working; the
manifest and pointer are additive.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
import zlib

import numpy as onp

from ..base import MXNetError
from . import faultsim

__all__ = ["CheckpointManager", "atomic_write_bytes", "capture_rng",
           "restore_rng"]


def atomic_write_bytes(path, data, inject_point="ckpt.write"):
    """Write ``data`` to ``path`` atomically: temp file in the same
    directory, fsync, then rename over the target (plus a directory
    fsync so the rename itself is durable).

    The fault-injection point fires MID-payload, so an armed
    ``ckpt.write:crash`` leaves a truncated *temp* file and the final
    path untouched — exactly the torn-write scenario the old direct
    ``nd.save`` could not survive.
    """
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            half = len(data) // 2
            f.write(data[:half])
            if inject_point:
                faultsim.inject(inject_point)
            f.write(data[half:])
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platforms/filesystems without directory fsync


def capture_rng():
    """Snapshot host (numpy) and device (mxnet_tpu._rng key) RNG state
    as JSON-serializable data, so a resumed run continues the exact
    random stream of the interrupted one."""
    st = onp.random.get_state()
    state = {"numpy": [st[0], onp.asarray(st[1]).tolist(), int(st[2]),
                       int(st[3]), float(st[4])],
             "device": None}
    try:
        import jax

        from .. import _rng

        if _rng._S.key is not None:
            state["device"] = onp.asarray(
                jax.random.key_data(_rng._S.key),
                onp.uint32).tolist()
    except Exception:
        pass  # key API absent or backend not initialized: host-only
    return state


def restore_rng(state):
    """Restore a :func:`capture_rng` snapshot (missing parts no-op)."""
    if not state:
        return
    np_st = state.get("numpy")
    if np_st:
        onp.random.set_state((np_st[0],
                              onp.asarray(np_st[1], onp.uint32),
                              int(np_st[2]), int(np_st[3]),
                              float(np_st[4])))
    dev = state.get("device")
    if dev is not None:
        try:
            import jax
            import jax.numpy as jnp

            from .. import _rng

            _rng._S.key = jax.random.wrap_key_data(
                jnp.asarray(dev, jnp.uint32))
        except Exception:
            pass


def _autotune_hash():
    """SHA-256 of the persisted autotune winners file, recorded so a
    resume can tell whether it is replaying under the same variant
    choices the checkpointed run trained with."""
    try:
        from .. import autotune

        p = autotune.cache_path()
        if os.path.exists(p):
            with open(p, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
    except Exception:
        pass
    return None


def _crc(blob):
    return zlib.crc32(blob) & 0xFFFFFFFF


def _as_nd(v):
    from .. import ndarray as nd

    data = v._data if isinstance(v, nd.NDArray) else v
    if hasattr(data, "sharding") and hasattr(data, "devices") \
            and len(data.devices()) > 1:
        # mesh-backed array (replicated module weights, or a ZeRO
        # bucket shard under optimizer_sharding="ps"): GATHER to one
        # host copy here — via host_gather, which also handles arrays
        # spanning PROCESSES on a real multi-host mesh — so what lands
        # on disk is the legacy world-size-agnostic single-array
        # layout and never aliases a device buffer a donating step may
        # consume mid-save
        from .elastic import host_gather

        return nd.array(host_gather(data))
    return v if isinstance(v, nd.NDArray) else nd.array(onp.asarray(v))


def _split_params(save_dict):
    """Split a loaded ``arg:``/``aux:``-keyed dict (the reference
    .params convention) into (arg_params, aux_params)."""
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


class CheckpointManager:
    """Owner of one checkpoint series under ``prefix``.

    Files per version ``NNNN`` (all written atomically, manifest after
    payloads, ``latest`` pointer last):

    * ``prefix-NNNN.params``        — ``arg:``/``aux:`` blobs, the
      reference binary format (``load_checkpoint`` compatible)
    * ``prefix-NNNN.states``        — pickled optimizer state (optional)
    * ``prefix-NNNN.manifest.json`` — epoch/step/cursor, per-payload
      size+CRC32, RNG snapshot, autotune winners hash
    * ``prefix-symbol.json``        — the network (shared across versions)
    * ``prefix-latest.json``        — pointer to the newest version

    Sharded-optimizer runs (``optimizer_sharding="ps"``): the save
    path GATHERS — mesh-backed params gather here in ``_as_nd`` and
    the ``ShardedBucketUpdater`` gathers its bucket shards into the
    legacy per-param states pickle before it reaches ``save`` — so the
    on-disk layout is identical to a replicated run's; loading into a
    sharded run RE-SHARDS (``ShardedBucketUpdater.set_states``), which
    is why ``.states`` files move freely between the two modes.
    """

    MANIFEST_FORMAT = 1

    def __init__(self, prefix, keep_n=None):
        self.prefix = os.fspath(prefix)
        self.keep_n = keep_n

    # ------------------------------------------------------------ paths
    def params_path(self, epoch):
        return f"{self.prefix}-{int(epoch):04d}.params"

    def states_path(self, epoch):
        return f"{self.prefix}-{int(epoch):04d}.states"

    def manifest_path(self, epoch):
        return f"{self.prefix}-{int(epoch):04d}.manifest.json"

    def symbol_path(self):
        return f"{self.prefix}-symbol.json"

    def latest_path(self):
        return f"{self.prefix}-latest.json"

    def _dir(self):
        return os.path.dirname(os.path.abspath(self.prefix)) or "."

    # ------------------------------------------------------------- save
    def save(self, version, symbol=None, arg_params=None,
             aux_params=None, optimizer_states=None, step=None,
             batch_cursor=0, extra=None, epoch=None, topology=None):
        """Write one atomic checkpoint version; returns its manifest.

        ``version`` names the files (``prefix-NNNN.*``); ``epoch`` is
        the training epoch recorded in the manifest and defaults to
        the version — they coincide for clean epoch-boundary saves,
        and diverge when fit's mid-epoch drain allocates a fresh
        version id to avoid rewriting an existing one in place.
        ``batch_cursor`` records how many batches of that epoch were
        already consumed (0 = a clean epoch boundary) — the resume
        cursor for mid-epoch preemption drains.

        ``topology`` (``resilience.elastic.topology_block``) stamps
        the world the checkpoint was written FROM — world size, mesh
        shape, optimizer-sharding mode, bucket-plan fingerprint,
        global batch — so a resume at a different world size can
        detect the mismatch and re-plan/re-shard instead of dying,
        while a same-topology resume provably skips the reshard.
        """
        t_save0 = time.perf_counter()
        version = int(version)
        epoch = version if epoch is None else int(epoch)
        arg_params = arg_params or {}
        aux_params = aux_params or {}
        save_dict = {f"arg:{k}": _as_nd(v) for k, v in
                     arg_params.items()}
        save_dict.update({f"aux:{k}": _as_nd(v) for k, v in
                          aux_params.items()})
        from .. import ndarray as nd

        files = {}
        payload = nd.save_buffer(save_dict)
        ppath = self.params_path(version)
        atomic_write_bytes(ppath, payload)
        files[os.path.basename(ppath)] = {
            "bytes": len(payload), "crc32": _crc(payload)}
        if optimizer_states is not None:
            spath = self.states_path(version)
            atomic_write_bytes(spath, optimizer_states)
            files[os.path.basename(spath)] = {
                "bytes": len(optimizer_states),
                "crc32": _crc(optimizer_states)}
        if symbol is not None:
            atomic_write_bytes(self.symbol_path(),
                               symbol.tojson().encode())
        manifest = {
            "format": self.MANIFEST_FORMAT,
            "version": version,
            "epoch": epoch,
            "step": step,
            "batch_cursor": int(batch_cursor),
            "files": files,
            "rng": capture_rng(),
            "autotune_sha256": _autotune_hash(),
            "topology": topology,
            "time": time.time(),
            "extra": extra or {},
        }
        atomic_write_bytes(self.manifest_path(version),
                           json.dumps(manifest, indent=1).encode())
        # the pointer goes LAST: a crash anywhere above leaves `latest`
        # naming the previous complete version
        atomic_write_bytes(
            self.latest_path(),
            json.dumps({"epoch": version,
                        "manifest": os.path.basename(
                            self.manifest_path(version))}).encode())
        self._apply_retention()
        from .. import telemetry

        telemetry.checkpoint_event(
            self.prefix, version, time.perf_counter() - t_save0,
            sum(f["bytes"] for f in files.values()))
        return manifest

    def _apply_retention(self):
        if not self.keep_n or int(self.keep_n) <= 0:
            return
        eps = self.epochs()
        for e in eps[:-int(self.keep_n)]:
            for p in (self.params_path(e), self.states_path(e),
                      self.manifest_path(e)):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # ----------------------------------------------------------- lookup
    def epochs(self):
        """All versions on disk (ascending), from their manifests."""
        base = os.path.basename(self.prefix)
        out = []
        try:
            names = os.listdir(self._dir())
        except OSError:
            return out
        suffix = ".manifest.json"
        for n in names:
            if n.startswith(base + "-") and n.endswith(suffix):
                num = n[len(base) + 1:-len(suffix)]
                if num.isdigit():
                    out.append(int(num))
        return sorted(out)

    def _read_manifest(self, epoch):
        with open(self.manifest_path(epoch), "rb") as f:
            return json.loads(f.read().decode())

    def has_manifest(self, epoch):
        return os.path.exists(self.manifest_path(epoch))

    def _read_verified(self, epoch):
        """Manifest + every payload in ONE read each, CRC-checked as
        read.  The recovery path (load) decodes from these buffers
        directly, so verification never doubles the disk I/O of a
        multi-GB resume."""
        man = self._read_manifest(epoch)
        blobs = {}
        for fname, meta in man["files"].items():
            fp = os.path.join(self._dir(), fname)
            with open(fp, "rb") as f:
                blob = f.read()
            if len(blob) != meta.get("bytes") \
                    or _crc(blob) != meta.get("crc32"):
                raise MXNetError(
                    f"checkpoint payload {fp!r} failed verification "
                    "(truncated or corrupt)")
            blobs[fname] = blob
        return man, blobs

    def verify(self, epoch):
        """True iff the manifest parses and every payload matches its
        recorded size and CRC32 — catches truncation, bit rot, and
        torn non-atomic writes from foreign tools."""
        try:
            self._read_verified(epoch)
            return True
        except (OSError, ValueError, KeyError, MXNetError):
            return False

    def _latest_candidates(self):
        """Version numbers to try, newest-first: the ``latest``
        pointer's target, then every other on-disk version."""
        candidates = []
        try:
            with open(self.latest_path(), "rb") as f:
                candidates.append(int(json.loads(f.read())["epoch"]))
        except (OSError, ValueError, KeyError):
            pass
        for e in reversed(self.epochs()):
            if e not in candidates:
                candidates.append(e)
        return candidates

    def latest_epoch(self):
        """Newest version that VERIFIES, or None.

        The ``latest`` pointer is consulted first; a corrupt or
        missing candidate falls back through older versions (newest
        first) — the previous-good-version guarantee.
        """
        for e in self._latest_candidates():
            if self.verify(e):
                return e
        return None

    # ------------------------------------------------------------- load
    def load(self, epoch=None, ctx=None):
        """Load a verified checkpoint.

        ``epoch=None`` loads the newest version that verifies (falling
        back past corrupt ones); an explicit version number raises
        :class:`MXNetError` when that version fails verification —
        detection, not silent substitution, for a pinned request.

        Returns a dict with ``version`` (the file id), ``epoch`` (the
        training epoch from the manifest — diverges from the version
        after mid-epoch drains), ``step``, ``batch_cursor``,
        ``arg_params``, ``aux_params`` (NDArray dicts),
        ``optimizer_states`` (bytes or None), ``rng``, ``topology``
        (the world stamp, or None for pre-elastic files) and
        ``extra``.
        """
        from .. import ndarray as nd

        man, blobs = {}, {}
        if epoch is None:
            # newest-good fallback, ONE read per candidate: the blobs
            # that verified are the blobs that get decoded
            t_load0 = time.perf_counter()
            skipped = []
            for cand in self._latest_candidates():
                try:
                    man, blobs = self._read_verified(cand)
                    epoch = cand
                    break
                except (OSError, ValueError, KeyError, MXNetError):
                    skipped.append(int(cand))
                    continue
            if epoch is None:
                raise MXNetError(
                    f"no verifiable checkpoint under {self.prefix!r}")
            if skipped:
                # the recovery was SILENT before: an operator whose
                # newest checkpoint is rotting learned it only when the
                # loss curve jumped back.  Emit a schema-valid
                # checkpoint record naming the skipped bad versions and
                # bump the ckpt_fallbacks counter (exported to the
                # Prometheus textfile) so the rot pages someone.
                from .. import telemetry

                telemetry.count("ckpt_fallbacks")
                telemetry.checkpoint_event(
                    self.prefix, epoch,
                    time.perf_counter() - t_load0,
                    sum(len(b) for b in blobs.values()),
                    reason="fallback", skipped_versions=skipped)
        else:
            epoch = int(epoch)
            if self.has_manifest(epoch):
                try:
                    man, blobs = self._read_verified(epoch)
                except MXNetError as e:
                    raise MXNetError(
                        f"checkpoint {self.params_path(epoch)!r} "
                        "failed verification (truncated or corrupt "
                        "payload); load(epoch=None) falls back to the "
                        "last good version") from e
            # manifest-less versions (pre-atomic-writer files) load
            # blind, the legacy behavior

        pname = os.path.basename(self.params_path(epoch))
        if pname in blobs:
            save_dict = nd.load_buffer(blobs[pname], ctx=ctx)
        else:
            save_dict = nd.load(self.params_path(epoch), ctx=ctx)
        arg_params, aux_params = _split_params(save_dict)
        sname = os.path.basename(self.states_path(epoch))
        states = blobs.get(sname)
        if states is None and os.path.exists(self.states_path(epoch)):
            with open(self.states_path(epoch), "rb") as f:
                states = f.read()
        return {
            "version": int(epoch),
            "epoch": int(man.get("epoch", epoch)),
            "step": man.get("step"),
            "batch_cursor": int(man.get("batch_cursor", 0)),
            "arg_params": arg_params,
            "aux_params": aux_params,
            "optimizer_states": states,
            "rng": man.get("rng"),
            "autotune_sha256": man.get("autotune_sha256"),
            "topology": man.get("topology"),
            "extra": man.get("extra", {}),
        }

    def load_params_dict(self, version, ctx=None):
        """One version's ``.params`` dict in a SINGLE read: with a
        manifest the payload is CRC-verified and decoded from the same
        buffer (raises on mismatch — detection for a pinned version);
        manifest-less files load blind, the legacy behavior."""
        from .. import ndarray as nd

        version = int(version)
        if self.has_manifest(version):
            try:
                _, blobs = self._read_verified(version)
            except (OSError, ValueError, KeyError, MXNetError) as e:
                raise MXNetError(
                    f"checkpoint {self.params_path(version)!r} failed "
                    "verification (truncated or corrupt payload); "
                    "CheckpointManager.load() falls back to the last "
                    "good version") from e
            pname = os.path.basename(self.params_path(version))
            if pname in blobs:
                return nd.load_buffer(blobs[pname], ctx=ctx)
        return nd.load(self.params_path(version), ctx=ctx)
