"""Preemption drain: SIGTERM/SIGINT-safe training.

A preempted TPU slice (or any orchestrator teardown) delivers SIGTERM;
the reference process just dies, losing the epoch in flight.
``Module.fit`` now installs a :class:`PreemptionDrain` around its epoch
loop: the signal only sets a flag, the in-flight step finishes, a final
checkpoint flushes (when fit owns a checkpoint manager), the
device-feed producer closes cleanly, and then the signal is re-raised
under its original disposition — TorchElastic-style job semantics,
where the relaunched ``fit(resume_from=...)`` continues bit-exactly.
"""
from __future__ import annotations

import os
import signal
import threading

__all__ = ["PreemptionDrain"]


class PreemptionDrain:
    """Context manager that converts termination signals to a drain
    request the training loop polls at step boundaries.

    Only the main thread can own signal handlers; entered from any
    other thread this is a no-op shell (``requested`` stays None), so
    fit keeps working inside worker threads and tests.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._prev = {}
        self._requested = None
        self._installed = False

    # ------------------------------------------------------- installed
    def __enter__(self):
        if threading.current_thread() is not threading.main_thread():
            return self
        try:
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._on_signal)
            self._installed = True
        except (ValueError, OSError):  # embedded interpreters etc.
            self._restore()
        return self

    def __exit__(self, *exc):
        self._restore()
        return False

    def _on_signal(self, signum, frame):
        # drain, don't die: the loop checks `requested` after the
        # in-flight step completes
        self._requested = signum
        try:
            # counter bump only (RunLog._lock is an RLock and handlers
            # run in the main thread, so this cannot deadlock); the
            # actual drain record + flight dump happen at the step
            # boundary in fit, not in signal context
            from .. import telemetry

            telemetry.count("preempt_signals")
        except Exception:
            pass

    def _restore(self):
        # keyed off _prev, not _installed: a PARTIAL install failure
        # (second signal.signal raised) must still put back the
        # handlers that did install, or the process is left with a
        # drain handler nothing polls — unkillable by SIGTERM
        for s, h in self._prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._prev = {}
        self._installed = False

    # -------------------------------------------------------- consumers
    @property
    def requested(self):
        """The signal number that requested the drain, or None."""
        return self._requested

    def reraise(self):
        """Re-deliver the drained signal under its ORIGINAL disposition.

        fit's contract is drain-then-die, not swallow: after the final
        checkpoint is flushed the process must still exit the way the
        orchestrator expects (default SIGTERM -> killed-by-15 status,
        default SIGINT -> KeyboardInterrupt).  No-op when nothing was
        requested.
        """
        sig = self._requested
        self._restore()
        if sig is None:
            return
        self._requested = None
        os.kill(os.getpid(), sig)
        # a default-disposition signal terminates before os.kill
        # returns control here; a handled/ignored one falls through —
        # surface SIGINT as the interrupt the caller expects
        if sig == signal.SIGINT:
            raise KeyboardInterrupt
