"""Optimizers.

Reference parity: python/mxnet/optimizer/optimizer.py (Optimizer registry,
per-param lr/wd multipliers, mixed-precision master weights, Updater) and
the fused optimizer *ops* in src/operator/optimizer_op.cc.

TPU-native redesign: each update rule is a pure jitted function
``(weight, grad, *state, lr, wd, ...) -> (new_weight, *new_state)``.
XLA fuses the whole rule into one kernel — the analog of the reference's
hand-fused SGD/Adam CUDA kernels — and jit caching per shape plays the
role of the reference's multi-tensor batching.  State lives in device
buffers between steps.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as onp

from .. import ndarray as nd
from ..base import MXNetError

__all__ = [
    "Optimizer", "SGD", "Signum", "NAG", "Adam", "AdamW", "AdaGrad",
    "RMSProp", "AdaDelta", "Adamax", "Nadam", "Ftrl", "FTML", "LARS",
    "SGLD", "DCASGD", "LBSGD", "Updater", "create", "register",
    "get_updater", "Test",
]

_REGISTRY: dict[str, type] = {}


def register(klass):
    name = klass.__name__.lower()
    _REGISTRY[name] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    if name.lower() not in _REGISTRY:
        raise MXNetError(f"Cannot find optimizer {name}")
    return _REGISTRY[name.lower()](**kwargs)


class Optimizer:
    """Base optimizer (reference optimizer.py:Optimizer).

    State handling: ``create_state(index, weight)`` returns a tuple of
    NDArrays; ``update(index, weight, grad, state)`` applies one step
    functionally (weight/state buffers are rebound, not mutated).
    """

    opt_registry = _REGISTRY  # reference-compat alias

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    create_optimizer = staticmethod(create)

    # ------------------------------------------------------------ lr / wd
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError(
                "LRScheduler of the optimizer has already been defined. "
                "Note that set_learning_rate can mutate the value of the "
                "learning rate of the optimizer only when the LRScheduler "
                "of the optimizer is undefined.")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _get_lr(self, index):
        lr = self.learning_rate
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    # ------------------------------------------------------------- state
    def create_state(self, index, weight):
        return ()

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (onp.float16,
                                                     jnp.bfloat16):
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (onp.float16,
                                                     jnp.bfloat16):
            master, base_state = state
            g32 = grad.astype("float32")
            self.update(index, master, g32, base_state)
            weight._adopt(master._data.astype(weight._data.dtype))
        else:
            self.update(index, weight, grad, state)

    # -------------------------------------------------- shared grad prep
    def _prep(self, grad_v):
        g = grad_v * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    # ------------------------------------------------ fused SPMD interface
    # make_train_step compiles fwd+bwd+update into ONE XLA program (the
    # analog of the reference's fused optimizer ops,
    # src/operator/optimizer_op.cc + contrib/multi_lars.cc); the optimizer
    # contributes a pure per-parameter rule.  Hyper-parameters are read
    # from self at trace time; lr schedulers are evaluated at self.lr's
    # trace-time value (step-dependent schedules re-trace on lr change).
    #: True for stochastic rules (SGLD) whose fused_update consumes the
    #: PRNG key; deterministic rules leave it False so make_train_step
    #: skips the per-parameter key fold-in (hundreds of dead scalar ops
    #: in the compiled step otherwise).
    needs_key = False

    def fused_state(self, w):
        """Initial per-parameter state as a tuple of jax arrays; mirrors
        create_state so eager and fused paths keep identical layouts."""
        return tuple(s._data for s in self.create_state(0, nd.NDArray(w)))

    def fused_update(self, w, g, state, t, key=None):
        """Pure update: (w, g, state, t[, key]) -> (new_w, new_state).

        w/g/state are jax arrays (or tracers inside pjit); t is the
        traced step count (1-based) for bias-corrected rules; key is a
        PRNG key for stochastic rules (SGLD).
        """
        raise MXNetError(
            f"{type(self).__name__} does not provide a fused SPMD rule")

    #: True when fused_update applies the same math to every element
    #: independently of its neighbors, so running it on an arbitrary
    #: slice of a flat dtype-homogeneous bucket of MANY parameters is
    #: identical to running it per-parameter — the contract the ZeRO-1
    #: sharded-server exchange (parallel.zero) relies on.  Norm-based
    #: rules (LARS, GroupAdaGrad) set False; LARS provides the
    #: bucket-aware form below instead.
    fused_elementwise = True

    def fused_bucket_update(self, w, g, state, t, key=None, seg_ids=None,
                            num_segments=None, axis_name=None):
        """Update one flat bucket SHARD (the server-side-optimizer
        analog, kvstore_dist_server.h:346).  ``w``/``g``/``state`` are
        this device's slice of the flat bucket; ``seg_ids`` maps each
        element to its parameter within the bucket and ``axis_name``
        names the shard axis, for rules needing cross-shard
        per-parameter reductions.  Default: delegate to the
        elementwise ``fused_update``."""
        if not self.fused_elementwise:
            raise MXNetError(
                f"{type(self).__name__} is not elementwise and provides "
                "no bucket-aware fused rule")
        return self.fused_update(w, g, state, t, key=key)


def _jit(fn):
    """jit with scalar hyper-params as traced args (no recompile per lr)."""
    return jax.jit(fn)


# ================================================================= rules
@_jit
def _sgd_step(w, g, lr, wd):
    return w - lr * (g + wd * w)


@_jit
def _sgd_mom_step(w, mom, g, lr, wd, momentum):
    mom = momentum * mom - lr * (g + wd * w)
    return w + mom, mom


@register
class SGD(Optimizer):
    """SGD with momentum (reference optimizer.py SGD; op
    src/operator/optimizer_op.cc sgd_update/sgd_mom_update).

    update: mom = momentum*mom - lr*(grad + wd*w); w += mom
    """

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._prep(grad._data)
        if self.momentum == 0.0:
            weight._adopt(_sgd_step(weight._data, g, lr, wd))
        else:
            (mom,) = state
            new_w, new_m = _sgd_mom_step(
                weight._data, mom._data, g, lr, wd, self.momentum)
            weight._adopt(new_w)
            mom._adopt(new_m)

    def fused_update(self, w, g, state, t, key=None):
        g = self._prep(g)
        if self.momentum == 0.0:
            # momentum may have been zeroed LIVE: pass any existing
            # slot through untouched (the eager rule leaves it stale
            # too) so the traced state structure never changes
            return _sgd_step(w, g, self.learning_rate, self.wd), state
        (mom,) = state
        new_w, new_m = _sgd_mom_step(w, mom, g, self.learning_rate,
                                     self.wd, self.momentum)
        return new_w, (new_m,)


@register
class Test(Optimizer):
    """Reference test optimizer: w += grad * rescale."""

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),)

    def update(self, index, weight, grad, state):
        weight._adopt(weight._data + grad._data * self.rescale_grad)

    def fused_update(self, w, g, state, t, key=None):
        return w + g * self.rescale_grad, state


@_jit
def _nag_step(w, mom, g, lr, wd, momentum):
    g = g + wd * w
    mom = momentum * mom + g
    return w - lr * (g + momentum * mom), mom


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._prep(grad._data)
        if self.momentum == 0.0:
            weight._adopt(_sgd_step(weight._data, g, lr, wd))
        else:
            (mom,) = state
            new_w, new_m = _nag_step(weight._data, mom._data, g, lr, wd,
                                     self.momentum)
            weight._adopt(new_w)
            mom._adopt(new_m)

    def fused_update(self, w, g, state, t, key=None):
        g = self._prep(g)
        if self.momentum == 0.0:
            # see SGD: live-zeroed momentum keeps the slot structure
            return _sgd_step(w, g, self.learning_rate, self.wd), state
        (mom,) = state
        new_w, new_m = _nag_step(w, mom, g, self.learning_rate, self.wd,
                                 self.momentum)
        return new_w, (new_m,)


@_jit
def _signum_step(w, mom, g, lr, wd, momentum, wd_lh):
    mom = momentum * mom - (1 - momentum) * (g + wd * w)
    return (1 - lr * wd_lh) * w + lr * jnp.sign(mom), mom


@register
class Signum(Optimizer):
    """signSGD / Signum (reference Signum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._prep(grad._data)
        if self.momentum == 0.0:
            weight._adopt(
                (1 - lr * self.wd_lh) * weight._data
                - lr * jnp.sign(g + wd * weight._data))
        else:
            (mom,) = state
            new_w, new_m = _signum_step(
                weight._data, mom._data, g, lr, wd, self.momentum,
                self.wd_lh)
            weight._adopt(new_w)
            mom._adopt(new_m)

    def fused_update(self, w, g, state, t, key=None):
        g = self._prep(g)
        lr, wd = self.learning_rate, self.wd
        if self.momentum == 0.0:
            # see SGD: live-zeroed momentum keeps the slot structure
            return ((1 - lr * self.wd_lh) * w
                    - lr * jnp.sign(g + wd * w)), state
        (mom,) = state
        new_w, new_m = _signum_step(w, mom, g, lr, wd, self.momentum,
                                    self.wd_lh)
        return new_w, (new_m,)


@_jit
def _adam_step(w, m, v, g, lr, wd, beta1, beta2, eps, t):
    g = g + wd * w
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    coef1 = 1.0 - beta1 ** t
    coef2 = 1.0 - beta2 ** t
    lr_t = lr * jnp.sqrt(coef2) / coef1
    return w - lr_t * m / (jnp.sqrt(v) + eps), m, v


@register
class Adam(Optimizer):
    """Adam (reference Adam; op adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        m, v = state
        g = self._prep(grad._data)
        new_w, new_m, new_v = _adam_step(
            weight._data, m._data, v._data, g, lr, wd, self.beta1,
            self.beta2, self.epsilon, float(t))
        weight._adopt(new_w)
        m._adopt(new_m)
        v._adopt(new_v)

    def fused_update(self, w, g, state, t, key=None):
        m, v = state
        new_w, new_m, new_v = _adam_step(
            w, m, v, self._prep(g), self.learning_rate, self.wd,
            self.beta1, self.beta2, self.epsilon, t)
        return new_w, (new_m, new_v)


@_jit
def _adamw_step(w, m, v, g, lr, eta, wd, beta1, beta2, eps, t):
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    coef1 = 1.0 - beta1 ** t
    coef2 = 1.0 - beta2 ** t
    lr_t = lr * jnp.sqrt(coef2) / coef1
    return w - eta * (lr_t * m / (jnp.sqrt(v) + eps) + wd * w), m, v


@register
class AdamW(Adam):
    """Decoupled weight decay Adam (reference
    src/operator/contrib/adamw.cc)."""

    def __init__(self, eta=1.0, **kwargs):
        super().__init__(**kwargs)
        self.eta = eta

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        m, v = state
        g = self._prep(grad._data)
        new_w, new_m, new_v = _adamw_step(
            weight._data, m._data, v._data, g, lr, self.eta, wd,
            self.beta1, self.beta2, self.epsilon, float(t))
        weight._adopt(new_w)
        m._adopt(new_m)
        v._adopt(new_v)

    def fused_update(self, w, g, state, t, key=None):
        m, v = state
        new_w, new_m, new_v = _adamw_step(
            w, m, v, self._prep(g), self.learning_rate, self.eta,
            self.wd, self.beta1, self.beta2, self.epsilon, t)
        return new_w, (new_m, new_v)


@_jit
def _adagrad_step(w, hist, g, lr, wd, eps):
    # reference adagrad op: history accumulates the raw grad^2, eps sits
    # inside the sqrt, and wd applies as a decoupled term
    hist = hist + g * g
    return w - lr * (g / jnp.sqrt(hist + eps) + wd * w), hist


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        (hist,) = state
        g = self._prep(grad._data)
        new_w, new_h = _adagrad_step(weight._data, hist._data, g, lr, wd,
                                     self.float_stable_eps)
        weight._adopt(new_w)
        hist._adopt(new_h)

    def fused_update(self, w, g, state, t, key=None):
        (hist,) = state
        new_w, new_h = _adagrad_step(w, hist, self._prep(g),
                                     self.learning_rate, self.wd,
                                     self.float_stable_eps)
        return new_w, (new_h,)


@_jit
def _group_adagrad_step(w, hist, g, lr, eps):
    hist = hist + jnp.mean(jnp.square(g), axis=tuple(range(1, g.ndim)),
                           keepdims=True)
    return w - lr * g / jnp.sqrt(hist + eps), hist


@register
class GroupAdaGrad(Optimizer):
    """Per-row (group) AdaGrad (reference
    python/mxnet/optimizer/contrib.py GroupAdaGrad + fused op
    src/operator/contrib/optimizer_op.cc group_adagrad_update):

        history += mean(square(grad), axis=1, keepdims=True)
        weight  -= lr * grad / sqrt(history + eps)

    One adaptive rate per output row — the embedding-table optimizer.
    Weight decay is not supported (reference contract).  Not
    bucket-shardable: the per-row history couples elements and no
    flat-bucket form exists, so ``optimizer_sharding="ps"`` rejects
    it."""

    fused_elementwise = False

    def __init__(self, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        assert len(weight.shape) >= 2, \
            "GroupAdaGrad needs >=2-dim weights (one group per row)"
        return (nd.zeros((weight.shape[0],) + (1,) *
                         (len(weight.shape) - 1),
                         ctx=weight.context, dtype=weight.dtype),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        assert self._get_wd(index) == 0.0, \
            "GroupAdaGrad does not support weight decay"
        lr = self._get_lr(index)
        (hist,) = state
        new_w, new_h = _group_adagrad_step(
            weight._data, hist._data, self._prep(grad._data), lr,
            self.float_stable_eps)
        weight._adopt(new_w)
        hist._adopt(new_h)

    def fused_update(self, w, g, state, t, key=None):
        assert self.wd == 0.0, \
            "GroupAdaGrad does not support weight decay"
        (hist,) = state
        new_w, new_h = _group_adagrad_step(
            w, hist, self._prep(g), self.learning_rate,
            self.float_stable_eps)
        return new_w, (new_h,)


@_jit
def _rmsprop_step(w, n, g, lr, wd, rho, eps):
    g = g + wd * w
    n = rho * n + (1 - rho) * g * g
    return w - lr * g / jnp.sqrt(n + eps), n


@_jit
def _rmsprop_alex_step(w, n, gavg, delta, g, lr, wd, rho, momentum, eps):
    g = g + wd * w
    n = rho * n + (1 - rho) * g * g
    gavg = rho * gavg + (1 - rho) * g
    delta = momentum * delta - lr * g / jnp.sqrt(n - gavg * gavg + eps)
    return w + delta, n, gavg, delta


@register
class RMSProp(Optimizer):
    """RMSProp (reference RMSProp; centered=True uses Alex Graves' variant)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._prep(grad._data)
        if self.centered:
            n, gavg, delta = state
            new_w, new_n, new_g, new_d = _rmsprop_alex_step(
                weight._data, n._data, gavg._data, delta._data, g, lr, wd,
                self.gamma1, self.gamma2, self.epsilon)
            weight._adopt(new_w)
            n._adopt(new_n)
            gavg._adopt(new_g)
            delta._adopt(new_d)
        else:
            (n,) = state
            new_w, new_n = _rmsprop_step(
                weight._data, n._data, g, lr, wd, self.gamma1, self.epsilon)
            weight._adopt(new_w)
            n._adopt(new_n)
        if self.clip_weights:
            weight._adopt(jnp.clip(weight._data, -self.clip_weights,
                                   self.clip_weights))

    def fused_update(self, w, g, state, t, key=None):
        g = self._prep(g)
        lr, wd = self.learning_rate, self.wd
        if self.centered:
            n, gavg, delta = state
            new_w, new_n, new_g, new_d = _rmsprop_alex_step(
                w, n, gavg, delta, g, lr, wd, self.gamma1, self.gamma2,
                self.epsilon)
            new_state = (new_n, new_g, new_d)
        else:
            (n,) = state
            new_w, new_n = _rmsprop_step(w, n, g, lr, wd, self.gamma1,
                                         self.epsilon)
            new_state = (new_n,)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        return new_w, new_state


@_jit
def _adadelta_step(w, acc_g, acc_delta, g, wd, rho, eps):
    g = g + wd * w
    acc_g = rho * acc_g + (1 - rho) * g * g
    delta = jnp.sqrt(acc_delta + eps) / jnp.sqrt(acc_g + eps) * g
    acc_delta = rho * acc_delta + (1 - rho) * delta * delta
    return w - delta, acc_g, acc_delta


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        g = self._prep(grad._data)
        new_w, new_ag, new_ad = _adadelta_step(
            weight._data, acc_g._data, acc_delta._data, g, wd, self.rho,
            self.epsilon)
        weight._adopt(new_w)
        acc_g._adopt(new_ag)
        acc_delta._adopt(new_ad)

    def fused_update(self, w, g, state, t, key=None):
        acc_g, acc_delta = state
        new_w, new_ag, new_ad = _adadelta_step(
            w, acc_g, acc_delta, self._prep(g), self.wd, self.rho,
            self.epsilon)
        return new_w, (new_ag, new_ad)


@_jit
def _adamax_step(w, m, u, g, lr, wd, beta1, beta2, t):
    g = g + wd * w
    m = beta1 * m + (1 - beta1) * g
    u = jnp.maximum(beta2 * u, jnp.abs(g))
    lr_t = lr / (1.0 - beta1 ** t)
    return w - lr_t * m / (u + 1e-8), m, u


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        m, u = state
        g = self._prep(grad._data)
        new_w, new_m, new_u = _adamax_step(
            weight._data, m._data, u._data, g, lr, wd, self.beta1,
            self.beta2, float(t))
        weight._adopt(new_w)
        m._adopt(new_m)
        u._adopt(new_u)

    def fused_update(self, w, g, state, t, key=None):
        m, u = state
        new_w, new_m, new_u = _adamax_step(
            w, m, u, self._prep(g), self.learning_rate, self.wd,
            self.beta1, self.beta2, t)
        return new_w, (new_m, new_u)


@_jit
def _nadam_step(w, m, v, g, lr, wd, beta1, beta2, eps, t, m_schedule,
                schedule_decay):
    g = g + wd * w
    momentum_t = beta1 * (1.0 - 0.5 * 0.96 ** (t * schedule_decay))
    momentum_t_1 = beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * schedule_decay))
    new_m_schedule = m_schedule * momentum_t
    m_schedule_next = new_m_schedule * momentum_t_1
    g_prime = g / (1.0 - new_m_schedule)
    m = beta1 * m + (1.0 - beta1) * g
    m_prime = m / (1.0 - m_schedule_next)
    v = beta2 * v + (1.0 - beta2) * g * g
    v_prime = v / (1.0 - beta2 ** t)
    m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
    return w - lr * m_bar / (jnp.sqrt(v_prime) + eps), m, v, new_m_schedule


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        m, v = state
        g = self._prep(grad._data)
        new_w, new_m, new_v, ms = _nadam_step(
            weight._data, m._data, v._data, g, lr, wd, self.beta1,
            self.beta2, self.epsilon, float(t), self.m_schedule,
            self.schedule_decay)
        self.m_schedule = float(ms)
        weight._adopt(new_w)
        m._adopt(new_m)
        v._adopt(new_v)

    def fused_state(self, w):
        # m_schedule is per-parameter carried state in the fused path
        # (the eager path keeps it as a python attribute)
        return (jnp.zeros_like(w), jnp.zeros_like(w),
                jnp.ones((), dtype=jnp.float32))

    def fused_update(self, w, g, state, t, key=None):
        m, v, m_schedule = state
        new_w, new_m, new_v, new_ms = _nadam_step(
            w, m, v, self._prep(g), self.learning_rate, self.wd,
            self.beta1, self.beta2, self.epsilon, t, m_schedule,
            self.schedule_decay)
        return new_w, (new_m, new_v, new_ms)


@_jit
def _ftrl_step(w, z, n, g, lr, wd, lamda1, beta):
    sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
    z = z + g - sigma * w
    n = n + g * g
    denom = wd + (beta + jnp.sqrt(n)) / lr
    new_w = jnp.where(
        jnp.abs(z) > lamda1,
        -(z - jnp.sign(z) * lamda1) / denom,
        jnp.zeros_like(w))
    return new_w, z, n


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        zst, n = state
        g = self._prep(grad._data)
        new_w, new_z, new_n = _ftrl_step(
            weight._data, zst._data, n._data, g, lr, wd, self.lamda1,
            self.beta)
        weight._adopt(new_w)
        zst._adopt(new_z)
        n._adopt(new_n)

    def fused_update(self, w, g, state, t, key=None):
        z, n = state
        new_w, new_z, new_n = _ftrl_step(
            w, z, n, self._prep(g), self.learning_rate, self.wd,
            self.lamda1, self.beta)
        return new_w, (new_z, new_n)


@_jit
def _ftml_step(w, d, s, z, g, lr, wd, beta1, beta2, eps, t):
    g = g + wd * w
    v = beta2 * s + (1 - beta2) * g * g
    d_t = (1.0 - beta1 ** t) / lr * (
        jnp.sqrt(v / (1.0 - beta2 ** t)) + eps)
    sigma_t = d_t - beta1 * d
    z = beta1 * z + (1.0 - beta1) * g - sigma_t * w
    return -z / d_t, d_t, v, z


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        return (z(), z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        d, s, zz = state
        g = self._prep(grad._data)
        new_w, new_d, new_s, new_z = _ftml_step(
            weight._data, d._data, s._data, zz._data, g, lr, wd,
            self.beta1, self.beta2, self.epsilon, float(t))
        weight._adopt(new_w)
        d._adopt(new_d)
        s._adopt(new_s)
        zz._adopt(new_z)

    def fused_update(self, w, g, state, t, key=None):
        d, s, z = state
        new_w, new_d, new_s, new_z = _ftml_step(
            w, d, s, z, self._prep(g), self.learning_rate, self.wd,
            self.beta1, self.beta2, self.epsilon, t)
        return new_w, (new_d, new_s, new_z)


@_jit
def _lars_step(w, mom, g, lr, wd, momentum, eta, eps):
    w_norm = jnp.linalg.norm(w)
    g_norm = jnp.linalg.norm(g)
    trust = jnp.where(
        (w_norm > 0) & (g_norm > 0),
        eta * w_norm / (g_norm + wd * w_norm + eps),
        jnp.ones_like(w_norm))
    scaled_lr = lr * trust
    mom = momentum * mom + scaled_lr * (g + wd * w)
    return w - mom, mom


def _lars_bucket_step(w, mom, g, seg_ids, lr, wd, momentum, eta, eps,
                      num_segments, axis_name=None):
    """LARS over one flat bucket shard: per-PARAMETER trust ratios from
    segment-summed squared norms, psum'd over the shard axis when a
    parameter spans shards (the multi_lars/multi_sum_sq pipeline,
    src/operator/contrib/multi_lars.cc, applied to the ZeRO layout)."""
    w_ss = jax.ops.segment_sum(w * w, seg_ids,
                               num_segments=num_segments)
    g_ss = jax.ops.segment_sum(g * g, seg_ids,
                               num_segments=num_segments)
    if axis_name is not None:
        w_ss = jax.lax.psum(w_ss, axis_name)
        g_ss = jax.lax.psum(g_ss, axis_name)
    w_norm = jnp.sqrt(w_ss)
    g_norm = jnp.sqrt(g_ss)
    trust = jnp.where((w_norm > 0) & (g_norm > 0),
                      eta * w_norm / (g_norm + wd * w_norm + eps),
                      jnp.ones_like(w_norm))
    scaled_lr = (lr * trust)[seg_ids]
    mom = momentum * mom + scaled_lr * (g + wd * w)
    return w - mom, mom


@register
class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling (reference optimizer.py:796 and
    the multi_lars fused ops, src/operator/contrib/multi_lars.cc)."""

    #: the trust ratio is a per-TENSOR norm, so the generic
    #: slice-the-bucket delegation is wrong; fused_bucket_update below
    #: recovers exact layer norms from segment sums + psum instead
    fused_elementwise = False

    def __init__(self, momentum=0.0, lars_eta=0.001, lars_epsilon=0,
                 momentum_correction=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = lars_eta
        self.epsilon = lars_epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        (mom,) = state
        g = self._prep(grad._data)
        new_w, new_m = _lars_step(
            weight._data, mom._data, g, lr, wd, self.momentum, self.eta,
            self.epsilon)
        weight._adopt(new_w)
        mom._adopt(new_m)

    def fused_update(self, w, g, state, t, key=None):
        (mom,) = state
        new_w, new_m = _lars_step(
            w, mom, self._prep(g), self.learning_rate, self.wd,
            self.momentum, self.eta, self.epsilon)
        return new_w, (new_m,)

    def fused_bucket_update(self, w, g, state, t, key=None, seg_ids=None,
                            num_segments=None, axis_name=None):
        if seg_ids is None:
            # whole-tensor bucket: degenerate to the per-param rule
            return self.fused_update(w, g, state, t, key=key)
        (mom,) = state
        new_w, new_m = _lars_bucket_step(
            w, mom, self._prep(g), seg_ids, self.learning_rate, self.wd,
            self.momentum, self.eta, self.epsilon, num_segments,
            axis_name)
        return new_w, (new_m,)


@register
class LBSGD(SGD):
    """Large-batch SGD with warmup (reference LBSGD; here LARS-style
    adaptive rate atop SGD semantics)."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(momentum=momentum,
                         multi_precision=multi_precision, **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference SGLD)."""

    needs_key = True

    def create_state(self, index, weight):
        return ()

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._prep(grad._data)
        noise = nd.random_normal(
            0, math.sqrt(lr), shape=weight.shape,
            dtype=str(weight.dtype) if weight.dtype != jnp.bfloat16
            else "float32")
        weight._adopt(
            weight._data - lr / 2 * (g + wd * weight._data)
            + noise._data.astype(weight._data.dtype))

    def fused_update(self, w, g, state, t, key=None):
        if key is None:
            raise MXNetError("SGLD fused rule needs a PRNG key")
        lr, wd = self.learning_rate, self.wd
        g = self._prep(g)
        noise = math.sqrt(lr) * jax.random.normal(
            key, w.shape, dtype=jnp.float32).astype(w.dtype)
        return w - lr / 2 * (g + wd * w) + noise, state


@_jit
def _dcasgd_step(w, mom, prev_w, g, lr, wd, momentum, lamda):
    g = g + wd * w
    mom = momentum * mom - lr * (g + lamda * g * g * (w - prev_w))
    return w + mom, mom, w


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        return (z(), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, prev_w = state
        g = self._prep(grad._data)
        new_w, new_m, new_prev = _dcasgd_step(
            weight._data, mom._data, prev_w._data, g, lr, wd,
            self.momentum, self.lamda)
        weight._adopt(new_w)
        mom._adopt(new_m)
        prev_w._adopt(new_prev)

    def fused_update(self, w, g, state, t, key=None):
        mom, prev_w = state
        new_w, new_m, new_prev = _dcasgd_step(
            w, mom, prev_w, self._prep(g), self.learning_rate, self.wd,
            self.momentum, self.lamda)
        return new_w, (new_m, new_prev)


# ================================================================ Updater
class Updater:
    """Applies an optimizer locally (reference optimizer.py:1943
    get_updater); used by KVStore local mode and Module."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            state = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states[index] = self._match_sharding(state, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(
            index, weight, grad, self.states[index])

    @staticmethod
    def _match_sharding(state, weight):
        """Place freshly-created state like its weight: under a Module
        data mesh the weight is replicated over N devices, and a state
        array committed to a single device would make the fused update
        a cross-committed-device error."""
        w = weight._data
        sharding = getattr(w, "sharding", None)
        if sharding is None or not hasattr(w, "devices") \
                or len(w.devices()) <= 1:
            return state

        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(sharding.mesh, PartitionSpec()) \
            if isinstance(sharding, NamedSharding) else None

        def place(s):
            if isinstance(s, (tuple, list)):
                return type(s)(place(x) for x in s)
            if isinstance(s, nd.NDArray):
                if s.shape == weight.shape:
                    s._data = jax.device_put(s._data, sharding)
                elif repl is not None:
                    # state with its own shape (GroupAdaGrad's per-row
                    # history): replicate over the same mesh so the
                    # fused update sees one consistent device set
                    s._data = jax.device_put(s._data, repl)
            return s

        return place(state)

    def get_states(self, dump_optimizer=False):
        import copy
        import pickle

        if dump_optimizer:
            # runtime handles (live Parameter objects) must not be
            # serialized: the reference excludes them, and pickling them
            # would both duplicate every weight tensor into the .states
            # file and detach lr_mult/wd_mult lookups from the live
            # parameters after load
            opt = copy.copy(self.optimizer)
            opt.param_dict = {}
            return pickle.dumps((self.states, opt))
        return pickle.dumps(self.states)

    def set_states(self, states):
        import pickle

        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, new_opt = states
            # reattach the live param_dict (stripped at save time)
            new_opt.param_dict = getattr(self.optimizer, "param_dict", {})
            self.optimizer = new_opt
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)


def get_updater(optimizer):
    return Updater(optimizer)
