"""Contrib optimizers namespace (reference
python/mxnet/optimizer/contrib.py).

GroupAdaGrad itself lives in the main registry (optimizer.py) so
``mx.optimizer.create('groupadagrad')`` resolves it like the reference;
this module mirrors the reference import surface."""
from .optimizer import GroupAdaGrad

__all__ = ["GroupAdaGrad"]
