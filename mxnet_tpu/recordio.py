"""RecordIO format: pack/unpack + (indexed) record file readers/writers.

Reference parity: python/mxnet/recordio.py (509 LoC: ``MXRecordIO``,
``MXIndexedRecordIO``, ``IRHeader``, pack/unpack/pack_img/unpack_img) and
the dmlc-core recordio framing (magic + cflag|length + payload + padding).
This implementation is pure Python but byte-compatible with the reference
file format so .rec datasets interchange.
"""
from __future__ import annotations

import ctypes  # noqa: F401  (API-compat import)
import numbers
import os
import struct
from collections import namedtuple

import numpy as onp

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "unpack_img", "pack_img"]

_kMagic = 0xCED7230A

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def _pad_size(n):
    return ((n + 3) // 4) * 4 - n


class MXRecordIO:
    """Sequential .rec reader/writer (reference MXRecordIO; C++ framing
    dmlc-core src/recordio.cc).

    ``resync=True`` (readers only) arms resync-on-magic: a torn or
    garbled frame no longer raises mid-stream — the reader scans
    forward to the next plausible magic boundary and returns the next
    whole record, reporting each gap via ``on_skip(offset,
    bytes_skipped, reason)``.  The dmlc continuation framing exists
    precisely so this is possible (see :meth:`write`).  Strict mode
    (the default — what write-side verification wants) raises exactly
    as before."""

    def __init__(self, uri, flag, resync=False, on_skip=None):
        self.uri = uri
        self.flag = flag
        self.fp = None
        self.is_open = False
        self._resync = bool(resync)
        self.on_skip = on_skip
        self.open()

    def open(self):
        if self.flag == "w":
            self.fp = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fp = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"Invalid flag {self.flag}")
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d.pop("fp", None)
        d.pop("on_skip", None)  # callbacks don't pickle portably
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.fp = None
        self.on_skip = None
        self._resync = d.get("_resync", False)
        is_open = d.get("is_open", False)
        self.is_open = False
        if is_open:
            self.open()

    def close(self):
        if self.is_open and self.fp is not None:
            self.fp.close()
            self.fp = None
        self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def _write_part(self, cflag, part):
        lrec = (cflag << 29) | len(part)
        self.fp.write(struct.pack("<II", _kMagic, lrec))
        self.fp.write(part)
        pad = _pad_size(len(part))
        if pad:
            self.fp.write(b"\x00" * pad)

    def write(self, buf):
        """Write one logical record.

        dmlc framing (dmlc-core src/recordio.cc): a payload containing
        the magic bytes is split at each occurrence into continuation
        parts — cflag 1=begin / 2=middle / 3=end, magic dropped from the
        parts and re-inserted by the reader — so the stream stays
        resynchronizable.
        """
        assert self.writable
        magic_bytes = struct.pack("<I", _kMagic)
        parts = []
        start = 0
        i = buf.find(magic_bytes)
        while i != -1:
            parts.append(buf[start:i])
            start = i + 4
            i = buf.find(magic_bytes, start)
        parts.append(buf[start:])
        if len(parts) == 1:
            self._write_part(0, parts[0])
        else:
            for j, part in enumerate(parts):
                cflag = 1 if j == 0 else (3 if j == len(parts) - 1 else 2)
                self._write_part(cflag, part)

    def _read_part(self):
        head = self.fp.read(8)
        if len(head) < 8:
            return None, None
        magic, lrec = struct.unpack("<II", head)
        if magic != _kMagic:
            raise MXNetError("Invalid record magic number")
        cflag = (lrec >> 29) & 0x7
        length = lrec & 0x1FFFFFFF
        buf = self.fp.read(length)
        if len(buf) != length:
            raise MXNetError(
                f"truncated record: expected {length} payload bytes, "
                f"got {len(buf)}")
        pad = _pad_size(length)
        if pad:
            self.fp.read(pad)
        return cflag, buf

    def _read_logical(self, check_first=False):
        cflag, buf = self._read_part()
        if buf is None:
            return None
        if cflag == 0:
            return buf
        if check_first and cflag not in (0, 1):
            # a resync scan can land on a continuation MIDDLE/END part
            # of a chain whose begin frame was lost; reassembling from
            # here would return a silently-truncated record
            raise MXNetError(
                f"record starts with continuation cflag {cflag} "
                "(orphaned multi-part tail)")
        parts = [buf]
        while cflag != 3:
            cflag, nxt = self._read_part()
            if nxt is None:
                raise MXNetError(
                    "truncated multi-part record at end of file")
            parts.append(nxt)
        return struct.pack("<I", _kMagic).join(parts)

    def read(self):
        """Read one logical record, reassembling continuation parts.

        Strict mode (default): any framing damage — bad magic,
        truncated payload, broken continuation chain — raises
        :class:`MXNetError` exactly where it is found.

        Resync mode (``resync=True``): the damage is skipped — scan
        forward to the next plausible frame boundary (magic at a
        4-byte-aligned offset whose header describes a frame that fits
        the file and chains onto another magic or EOF) and return the
        next WHOLE record.  Every gap is reported through
        ``on_skip(offset, bytes_skipped, reason)`` and counted on the
        ``io_resyncs`` telemetry counter; reaching EOF mid-scan
        returns None like a clean end of stream.
        """
        assert not self.writable
        from .resilience import faultsim

        if not self._resync:
            faultsim.inject("io.read")  # an armed raise = a torn frame
            return self._read_logical()
        gap = None  # (start offset, first reason) of the current gap
        while True:
            start = self.fp.tell()
            try:
                faultsim.inject("io.read")
                rec = self._read_logical(check_first=True)
            except (MXNetError, faultsim.FaultInjected) as exc:
                # consecutive failures merge into ONE reported gap —
                # a torn multi-part chain or a long corrupt extent is
                # one region lost, not one skip event per bad frame
                if gap is None:
                    gap = (start, str(exc))
                if self._resync_scan(start + 4) is None:
                    self._report_skip(gap[0],
                                      self._file_size() - gap[0],
                                      gap[1])
                    return None
                continue
            if gap is not None:
                self._report_skip(gap[0], start - gap[0], gap[1])
            return rec

    def _file_size(self):
        return os.fstat(self.fp.fileno()).st_size

    def _report_skip(self, offset, nbytes, reason):
        try:
            from . import telemetry

            telemetry.count("io_resyncs")
            telemetry.event("io_resync", file=self.uri,
                            offset=int(offset),
                            bytes_skipped=int(nbytes), reason=reason)
        except Exception:
            pass  # telemetry must never break the read path
        if self.on_skip is not None:
            self.on_skip(int(offset), int(nbytes), reason)

    def _plausible_frame(self, pos, size):
        """Whether a frame starting at ``pos`` could be real: magic,
        sane cflag, a length that fits the file, and the frame's end
        landing on EOF or another magic (payloads can contain stray
        magic-looking bytes — chaining to the NEXT boundary rejects
        them)."""
        here = self.fp.tell()
        try:
            self.fp.seek(pos)
            head = self.fp.read(8)
            if len(head) < 8:
                return False
            magic, lrec = struct.unpack("<II", head)
            if magic != _kMagic:
                return False
            length = lrec & 0x1FFFFFFF
            end = pos + 8 + length + _pad_size(length)
            if end > size:
                return False
            if end == size:
                return True
            self.fp.seek(end)
            nxt = self.fp.read(4)
            return len(nxt) == 4 and \
                struct.unpack("<I", nxt)[0] == _kMagic
        finally:
            self.fp.seek(here)

    def _resync_scan(self, from_pos):
        """Scan forward from ``from_pos`` for the next plausible frame
        boundary (frames are 4-byte aligned by the writer's padding);
        position the fp there and return the offset, or None (fp at
        EOF) when no further record exists."""
        size = self._file_size()
        magic_bytes = struct.pack("<I", _kMagic)
        pos = max(0, int(from_pos))
        pos += (-pos) % 4  # align up
        chunk = 1 << 16
        while pos < size:
            self.fp.seek(pos)
            buf = self.fp.read(chunk + 8)
            i = buf.find(magic_bytes)
            while i != -1:
                cand = pos + i
                if cand % 4 == 0 and cand + 8 <= size \
                        and self._plausible_frame(cand, size):
                    self.fp.seek(cand)
                    return cand
                i = buf.find(magic_bytes, i + 1)
            pos += chunk
        self.fp.seek(size)
        return None

    def tell(self):
        return self.fp.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access .rec via a .idx sidecar (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        pos = self.idx[idx]
        self.fp.seek(pos)

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack an IRHeader + byte string (reference recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = onp.asarray(header.label, dtype=onp.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                    header.id2) + s
    return s


def unpack(s):
    """Unpack to (IRHeader, payload bytes) (reference recordio.py unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        header = header._replace(
            label=onp.frombuffer(s, onp.float32, header.flag))
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    """Unpack a packed image record to (header, BGR ndarray)."""
    header, s = unpack(s)
    img = _imdecode(onp.frombuffer(s, dtype=onp.uint8), iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (requires cv2; gated in this environment)."""
    try:
        import cv2
    except ImportError:
        raise MXNetError(
            "pack_img requires opencv (cv2), unavailable in this "
            "environment; pack pre-encoded bytes with pack() instead.")
    encode_params = None
    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())


def _imdecode(buf, iscolor=-1):
    try:
        import cv2

        return cv2.imdecode(buf, iscolor)
    except ImportError:
        pass
    try:
        from PIL import Image
        import io as _io

        img = onp.asarray(Image.open(_io.BytesIO(buf.tobytes())))
        if img.ndim == 3:
            img = img[..., ::-1]  # RGB -> BGR to match cv2 convention
        return img
    except ImportError:
        raise MXNetError(
            "image decode requires cv2 or PIL; neither is available")
