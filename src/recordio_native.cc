// Native data-plane hot path: RecordIO parsing + threaded JPEG decode +
// augment + batch assembly.
//
// Reference parity: src/io/iter_image_recordio_2.cc:880 (threaded
// record->decode->augment->batch pipeline) + image_aug_default.cc
// (crop/resize/mirror chain) + dmlc recordio framing.  The reference
// runs this in C++ worker threads because Python cannot feed GPUs; the
// same holds for TPU hosts, so the decode loop lives here and Python
// drives it through ctypes (the GIL is released for the whole batch).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 recordio_native.cc -o
//        librecordio_native.so -ljpeg -lpthread
#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <csetjmp>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;  // dmlc recordio magic

inline uint32_t DecodeLFlag(uint32_t rec) { return (rec >> 29U) & 7U; }
inline uint32_t DecodeLength(uint32_t rec) {
  return rec & ((1U << 29U) - 1U);
}

struct JerrMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void JerrExit(j_common_ptr cinfo) {
  JerrMgr* err = reinterpret_cast<JerrMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// decode one JPEG into rgb (h*w*3); returns 0 on success
int DecodeJpeg(const uint8_t* data, int64_t len, std::vector<uint8_t>* out,
               int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JerrExit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *h = cinfo.output_height;
  *w = cinfo.output_width;
  out->resize(static_cast<size_t>(*h) * *w * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// bilinear resize rgb (sh, sw) -> (dh, dw); int64 pixel indexing —
// legal JPEG dims reach 65535 and h*w*3 overflows 32-bit int
void ResizeBilinear(const uint8_t* src, int sh, int sw, uint8_t* dst,
                    int dh, int dw) {
  const float sy = dh > 1 ? float(sh - 1) / (dh - 1) : 0.f;
  const float sx = dw > 1 ? float(sw - 1) / (dw - 1) : 0.f;
  const int64_t ssw = sw, sdw = dw;
  for (int y = 0; y < dh; ++y) {
    float fy = y * sy;
    int64_t y0 = static_cast<int64_t>(fy);
    int64_t y1 = std::min<int64_t>(y0 + 1, sh - 1);
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = x * sx;
      int64_t x0 = static_cast<int64_t>(fx);
      int64_t x1 = std::min<int64_t>(x0 + 1, sw - 1);
      float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(y0 * ssw + x0) * 3 + c];
        float v01 = src[(y0 * ssw + x1) * 3 + c];
        float v10 = src[(y1 * ssw + x0) * 3 + c];
        float v11 = src[(y1 * ssw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(static_cast<int64_t>(y) * sdw + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Parse dmlc recordio framing: fills offsets/sizes (payload only, with
// continuation parts merged logically impossible without copy — this
// returns per-part extents; python merges rare multi-part records).
// Returns number of records, or -1 on framing error.
int64_t rec_parse(const uint8_t* buf, int64_t len, int64_t* offsets,
                  int64_t* sizes, uint32_t* lflags, int64_t max_records) {
  int64_t pos = 0;
  int64_t n = 0;
  while (pos + 8 <= len && n < max_records) {
    uint32_t magic;
    std::memcpy(&magic, buf + pos, 4);
    if (magic != kMagic) return -1;
    uint32_t lrec;
    std::memcpy(&lrec, buf + pos + 4, 4);
    uint32_t l = DecodeLength(lrec);
    offsets[n] = pos + 8;
    sizes[n] = l;
    lflags[n] = DecodeLFlag(lrec);
    ++n;
    int64_t upsize = ((l + 3U) >> 2U) << 2U;
    pos += 8 + upsize;
  }
  return n;
}

// Decode + augment one batch of JPEGs in parallel.
//  jpegs: concatenated jpeg bytes; joff/jlen: per-image extents (n)
//  out: float32 batch buffer (n, 3, H, W) NCHW, normalized with
//       mean/std per channel; rand_* arrays drive augmentation:
//  crop_x/crop_y in [0,1] relative crop origin, mirror in {0,1},
//  resize_short: if > 0, resize shorter side to it before cropping.
// Returns count of failed decodes (their slots are zero-filled).
int64_t decode_augment_batch(
    const uint8_t* jpegs, const int64_t* joff, const int64_t* jlen,
    int64_t n, float* out, int64_t out_h, int64_t out_w,
    const float* mean, const float* std_, const float* crop_x,
    const float* crop_y, const uint8_t* mirror, int resize_short,
    int num_threads) {
  std::atomic<int64_t> fail{0};
  std::atomic<int64_t> next{0};
  int nt = num_threads > 0
               ? num_threads
               : std::max(1U, std::thread::hardware_concurrency());
  auto worker = [&]() {
    std::vector<uint8_t> rgb, resized, cropped;
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      int h = 0, w = 0;
      float* dst = out + i * 3 * out_h * out_w;
      if (DecodeJpeg(jpegs + joff[i], jlen[i], &rgb, &h, &w) != 0) {
        std::memset(dst, 0, sizeof(float) * 3 * out_h * out_w);
        fail.fetch_add(1);
        continue;
      }
      const uint8_t* cur = rgb.data();
      if (resize_short > 0) {
        int nh, nw;
        if (h < w) {
          nh = resize_short;
          nw = static_cast<int>(1.0 * w * resize_short / h + 0.5);
        } else {
          nw = resize_short;
          nh = static_cast<int>(1.0 * h * resize_short / w + 0.5);
        }
        resized.resize(static_cast<size_t>(nh) * nw * 3);
        ResizeBilinear(cur, h, w, resized.data(), nh, nw);
        cur = resized.data();
        h = nh;
        w = nw;
      }
      // crop to (out_h, out_w) at relative origin; if the image is
      // smaller, bilinear-resize the full frame instead
      if (h >= out_h && w >= out_w) {
        int x0 = static_cast<int>(crop_x[i] * (w - out_w));
        int y0 = static_cast<int>(crop_y[i] * (h - out_h));
        cropped.resize(static_cast<size_t>(out_h) * out_w * 3);
        for (int y = 0; y < out_h; ++y) {
          std::memcpy(cropped.data() + static_cast<size_t>(y) * out_w * 3,
                      cur + ((y0 + y) * static_cast<int64_t>(w) + x0) * 3,
                      static_cast<size_t>(out_w) * 3);
        }
        cur = cropped.data();
      } else {
        cropped.resize(static_cast<size_t>(out_h) * out_w * 3);
        ResizeBilinear(cur, h, w, cropped.data(), out_h, out_w);
        cur = cropped.data();
      }
      // HWC uint8 -> NCHW float32 normalized (+ optional mirror)
      for (int c = 0; c < 3; ++c) {
        float m = mean ? mean[c] : 0.f;
        float s = std_ ? std_[c] : 1.f;
        float* plane = dst + static_cast<int64_t>(c) * out_h * out_w;
        for (int y = 0; y < out_h; ++y) {
          for (int x = 0; x < out_w; ++x) {
            int sx = mirror && mirror[i] ? (out_w - 1 - x) : x;
            plane[y * out_w + x] =
                (cur[(y * out_w + sx) * 3 + c] - m) / s;
          }
        }
      }
    }
  };
  std::vector<std::thread> threads;
  for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  return fail.load();
}

// plain decode of one jpeg into caller buffer (h*w*3, caller queried
// size via rec_jpeg_size)
int rec_jpeg_size(const uint8_t* data, int64_t len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JerrExit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  *h = cinfo.image_height;
  *w = cinfo.image_width;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int rec_jpeg_decode(const uint8_t* data, int64_t len, uint8_t* out,
                    int h, int w) {
  std::vector<uint8_t> rgb;
  int dh = 0, dw = 0;
  if (DecodeJpeg(data, len, &rgb, &dh, &dw) != 0) return 1;
  if (dh != h || dw != w) return 2;
  std::memcpy(out, rgb.data(), rgb.size());
  return 0;
}

}  // extern "C"
