// Native parameter-server shard (the C++ runtime analog of ps-lite's
// server, src/kvstore/kvstore_dist_server.h).  One shard per worker
// process; the Python client (mxnet_tpu/_ps.py) speaks a little-endian
// binary protocol to it.  Semantics mirror the Python _ServerShard
// exactly: sync pushes merge all W workers per round (round-aware
// pulls), async pushes apply immediately, heartbeats feed the
// get_num_dead_node probe.  Optimizer rules registered from Python run
// through a C callback (the reference ships optimizers to its servers
// the same way, just compiled in).
//
// Values carry their NATIVE dtype end to end (reference
// kvstore_dist_server.h stores received blobs as-is): the wire frames
// tag every payload with a dtype code, the server stores raw bytes in
// that dtype, and merge arithmetic widens through double per element.
// dtype codes: 0=f32 1=f64 2=bf16 3=f16 4=s32 5=s64 6=s8 7=u8.
//
// Wire format (all little-endian):
//   request  = [u64 len][u8 op][u32 klen][key bytes][op payload]
//     op 0 INIT: [i32 sender][u8 refill][u8 dt][u64 n][elem x n]
//                refill=1 (shard-restart recovery) is set-if-absent:
//                it never clobbers re-accumulated pushes
//     op 1 PUSH: [i32 sender][u8 mode 0=sync 1=async][u8 compressed]
//                [u8 dt][f32 threshold][u64 n][payload: elem x n, or
//                 u8 x ceil(n/4) packed 2-bit codes]
//     op 2 PULL: [i32 sender]
//     op 3 HB:   [i32 sender]
//     op 4 DEAD: [f64 timeout_sec]
//     op 5 SPUSH: [i32 sender][u8 mode][u8 dt][u64 nrows][u64 rowlen]
//                 [i64 rows x nrows][elem x nrows*rowlen]
//                 row-sparse push: only touched rows cross the wire
//                 (reference kvstore_dist.h PushRowSparse)
//     op 6 SPULL: [i32 sender][u64 nrows][u64 rowlen][i64 rows x nrows]
//                 responds VAL with the rows' elems (PullRowSparseImpl)
//     op 7 CMD:  [i32 head][u32 blen][body bytes] — the
//                SendCommandToServers channel; head==0 drives the
//                server profiler (profile:start/stop/dump:<path>, the
//                KVStoreServerProfilerCommand analog)
//   response = [u64 len][u8 status][payload]
//     status 0 OK: empty      status 1 ERR: utf-8 message
//     status 2 VAL: [u8 dt][u64 n][elem x n]
//     status 3 DEAD: [u32 m][i32 x m ranks]

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// updater callback provided by Python: applies the optimizer rule for
// `key` to `value` (length n) given `grad`.  Returns 0 if it applied,
// 1 if no rule is registered (server uses default merge semantics),
// and < 0 on a Python-side error — the server must surface that to
// the client, NOT fall back silently.  Runs under the server
// connection thread; the Python side re-acquires the GIL (ctypes does
// this automatically).  f32-only: non-f32 keys use default merge.
typedef int (*updater_fn)(const char* key, const float* grad,
                          float* value, uint64_t n);

// ----------------------------------------------------- dtype helpers
size_t esize(uint8_t dt) {
  switch (dt) {
    case 1: case 5: return 8;   // f64, s64
    case 2: case 3: return 2;   // bf16, f16
    case 6: case 7: return 1;   // s8, u8
    default: return 4;          // f32, s32
  }
}

float half_to_float(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while (!(mant & 0x400u)) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3ffu;
      bits = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 0x1f) {
    bits = sign | 0x7f800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t float_to_half(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (exp <= 0) return static_cast<uint16_t>(sign);  // flush to zero
  if (exp >= 0x1f)
    return static_cast<uint16_t>(sign | 0x7c00u |
                                 ((bits & 0x7f800000u) == 0x7f800000u
                                      ? (mant ? 0x200u : 0u)
                                      : 0u));
  return static_cast<uint16_t>(sign | (exp << 10) | (mant >> 13));
}

double get_el(const char* p, uint8_t dt, uint64_t i) {
  switch (dt) {
    case 0: { float v; std::memcpy(&v, p + 4 * i, 4); return v; }
    case 1: { double v; std::memcpy(&v, p + 8 * i, 8); return v; }
    case 2: { uint16_t h; std::memcpy(&h, p + 2 * i, 2);
              uint32_t b = static_cast<uint32_t>(h) << 16;
              float v; std::memcpy(&v, &b, 4); return v; }
    case 3: { uint16_t h; std::memcpy(&h, p + 2 * i, 2);
              return half_to_float(h); }
    case 4: { int32_t v; std::memcpy(&v, p + 4 * i, 4); return v; }
    case 5: { int64_t v; std::memcpy(&v, p + 8 * i, 8);
              return static_cast<double>(v); }
    case 6: { int8_t v; std::memcpy(&v, p + i, 1); return v; }
    default: { uint8_t v; std::memcpy(&v, p + i, 1); return v; }
  }
}

void set_el(char* p, uint8_t dt, uint64_t i, double v) {
  switch (dt) {
    case 0: { float f = static_cast<float>(v);
              std::memcpy(p + 4 * i, &f, 4); break; }
    case 1: std::memcpy(p + 8 * i, &v, 8); break;
    case 2: { float f = static_cast<float>(v);
              uint32_t b; std::memcpy(&b, &f, 4);
              // round-to-nearest-even on the dropped 16 bits
              uint32_t rounded = b + 0x7fffu + ((b >> 16) & 1u);
              uint16_t h = static_cast<uint16_t>(rounded >> 16);
              std::memcpy(p + 2 * i, &h, 2); break; }
    case 3: { uint16_t h = float_to_half(static_cast<float>(v));
              std::memcpy(p + 2 * i, &h, 2); break; }
    case 4: { int32_t x = static_cast<int32_t>(v);
              std::memcpy(p + 4 * i, &x, 4); break; }
    case 5: { int64_t x = static_cast<int64_t>(v);
              std::memcpy(p + 8 * i, &x, 8); break; }
    case 6: { int8_t x = static_cast<int8_t>(v);
              std::memcpy(p + i, &x, 1); break; }
    default: { uint8_t x = static_cast<uint8_t>(v);
               std::memcpy(p + i, &x, 1); break; }
  }
}

struct TVal {
  uint8_t dt = 0;
  uint64_t n = 0;
  std::vector<char> raw;
};

struct Shard {
  int rank = 0;
  int size = 1;
  int listen_fd = -1;
  int port = 0;
  updater_fn updater = nullptr;

  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, TVal> values;
  // merge accumulators widen through double for every dtype
  std::unordered_map<std::string, std::vector<double>> pending;
  std::unordered_map<std::string, int> pending_count;
  std::unordered_map<std::string, long> completed_rounds;
  std::map<std::pair<std::string, int>, long> pushed_rounds;
  std::unordered_map<int, double> last_hb;
  std::vector<std::thread> threads;
  bool stopping = false;
  // server-side profiling (KVStoreServerProfilerCommand analog)
  bool profiling = false;
  uint64_t n_push = 0, n_pull = 0, n_spush = 0, n_spull = 0;
  uint64_t bytes_in = 0, bytes_out = 0;
};

Shard* g_shard = nullptr;

double now_sec() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void put_u64(std::vector<char>* out, uint64_t v) {
  out->insert(out->end(), reinterpret_cast<char*>(&v),
              reinterpret_cast<char*>(&v) + 8);
}

bool send_resp(int fd, uint8_t status, const std::vector<char>& body) {
  uint64_t len = 1 + body.size();
  std::vector<char> frame;
  frame.reserve(8 + len);
  put_u64(&frame, len);
  frame.push_back(static_cast<char>(status));
  frame.insert(frame.end(), body.begin(), body.end());
  return write_all(fd, frame.data(), frame.size());
}

bool send_err(int fd, const std::string& msg) {
  std::vector<char> body(msg.begin(), msg.end());
  return send_resp(fd, 1, body);
}

bool send_val(int fd, uint8_t dt, const char* data, uint64_t n) {
  std::vector<char> body;
  body.reserve(9 + n * esize(dt));
  body.push_back(static_cast<char>(dt));
  put_u64(&body, n);
  body.insert(body.end(), data, data + n * esize(dt));
  return send_resp(fd, 2, body);
}

// decode the 2-bit packed payload (see GradientCompression): code 1 ->
// +t, 2 -> -t, 0/3 -> 0
void decompress_2bit(const uint8_t* p, uint64_t n, float t,
                     std::vector<double>* out) {
  out->assign(n, 0.0);
  for (uint64_t i = 0; i < n; ++i) {
    uint8_t code = (p[i >> 2] >> ((i & 3) * 2)) & 3;
    if (code == 1)
      (*out)[i] = t;
    else if (code == 2)
      (*out)[i] = -t;
  }
}

// returns 0 on success, -1 if the python updater errored (the caller
// must send an error response and leave the value untouched)
int apply_update(Shard* s, const std::string& key,
                 const std::vector<double>& grad, bool is_async) {
  // caller holds s->mu
  TVal& val = s->values[key];
  if (s->updater != nullptr) {
    // the optimizer callback speaks f32; non-f32 values round-trip
    // through an f32 view so the rule applies to EVERY dtype exactly
    // like the python shard does (silently skipping it would make the
    // two interchangeable transports diverge)
    std::vector<float> g32(grad.begin(), grad.end());
    int rc;
    if (val.dt == 0) {
      rc = s->updater(key.c_str(), g32.data(),
                      reinterpret_cast<float*>(val.raw.data()), val.n);
    } else {
      std::vector<float> v32(val.n);
      for (uint64_t i = 0; i < val.n; ++i)
        v32[i] = static_cast<float>(
            get_el(val.raw.data(), val.dt, i));
      rc = s->updater(key.c_str(), g32.data(), v32.data(), val.n);
      if (rc == 0)
        for (uint64_t i = 0; i < val.n; ++i)
          set_el(val.raw.data(), val.dt, i, v32[i]);
    }
    if (rc == 0) return 0;  // python rule applied
    if (rc < 0) return -1;  // python rule RAISED: surface, don't merge
  }
  if (is_async) {
    for (uint64_t i = 0; i < val.n; ++i)
      set_el(val.raw.data(), val.dt, i,
             get_el(val.raw.data(), val.dt, i) + grad[i]);
  } else {
    for (uint64_t i = 0; i < val.n; ++i)
      set_el(val.raw.data(), val.dt, i, grad[i]);
  }
  return 0;
}

void serve_conn_inner(Shard* s, int fd) {
  std::vector<char> buf;
  for (;;) {
    uint64_t len = 0;
    if (!read_exact(fd, &len, 8)) break;
    // 1 GiB frame cap: anything larger is a corrupt/foreign peer (a
    // pickle client's big-endian length, version skew), not data
    if (len < 5 || len > (1ull << 30)) break;
    buf.resize(len);
    if (!read_exact(fd, buf.data(), len)) break;
    const char* p = buf.data();
    uint8_t op = static_cast<uint8_t>(*p++);
    uint32_t klen;
    std::memcpy(&klen, p, 4);
    p += 4;
    if (static_cast<uint64_t>(klen) > len - 5) {
      send_err(fd, "malformed frame");
      continue;
    }
    std::string key(p, p + klen);
    p += klen;
    const char* end = buf.data() + len;
    // fixed per-op header sizes: reject truncated frames BEFORE any
    // header memcpy (a crashed/version-skewed peer must cost an error
    // response, not an out-of-bounds read)
    static const uint64_t kHeader[8] = {14, 19, 4, 4, 8, 22, 20, 8};
    if (op > 7 || static_cast<uint64_t>(end - p) < kHeader[op]) {
      send_err(fd, "truncated frame");
      continue;
    }

    if (op == 0) {  // INIT
      int32_t sender;
      uint8_t refill, dt;
      uint64_t n;
      std::memcpy(&sender, p, 4);
      p += 4;
      refill = static_cast<uint8_t>(*p++);
      dt = static_cast<uint8_t>(*p++);
      std::memcpy(&n, p, 8);
      p += 8;
      if (dt > 7 || n > static_cast<uint64_t>(end - p) / esize(dt)) {
        send_err(fd, "short init payload");
        continue;
      }
      std::unique_lock<std::mutex> lk(s->mu);
      if ((sender == 0 && !refill) ||
          s->values.find(key) == s->values.end()) {
        TVal& v = s->values[key];
        v.dt = dt;
        v.n = n;
        v.raw.assign(p, p + n * esize(dt));
      }
      s->cv.notify_all();
      lk.unlock();
      send_resp(fd, 0, {});
    } else if (op == 1) {  // PUSH
      int32_t sender;
      uint8_t mode, compressed, dt;
      float threshold;
      uint64_t n;
      std::memcpy(&sender, p, 4);
      p += 4;
      mode = static_cast<uint8_t>(*p++);
      compressed = static_cast<uint8_t>(*p++);
      dt = static_cast<uint8_t>(*p++);
      std::memcpy(&threshold, p, 4);
      p += 4;
      std::memcpy(&n, p, 8);
      p += 8;
      std::vector<double> grad;
      if (compressed) {
        if (n > (1ull << 33) ||
            (n + 3) / 4 > static_cast<uint64_t>(end - p)) {
          send_err(fd, "short packed payload");
          continue;
        }
        decompress_2bit(reinterpret_cast<const uint8_t*>(p), n,
                        threshold, &grad);
      } else {
        if (dt > 7 ||
            n > static_cast<uint64_t>(end - p) / esize(dt)) {
          send_err(fd, "short push payload");
          continue;
        }
        grad.resize(n);
        for (uint64_t i = 0; i < n; ++i) grad[i] = get_el(p, dt, i);
      }
      std::unique_lock<std::mutex> lk(s->mu);
      auto it = s->values.find(key);
      if (it == s->values.end() || it->second.n != n) {
        lk.unlock();
        send_err(fd, "push to uninitialized key " + key);
        continue;
      }
      if (s->profiling) {
        s->n_push++;
        s->bytes_in += compressed ? (n + 3) / 4 : n * esize(dt);
      }
      int urc = 0;
      if (mode == 1) {  // async: apply immediately
        urc = apply_update(s, key, grad, /*is_async=*/true);
      } else {  // sync: merge all W workers, then update once
        // round-skew guard (mirrors _ServerShard): a second push from
        // the same worker before the in-flight round merges would
        // collapse two of its grads into one round — wait for the
        // merge (blocking stalls only this connection's thread; the
        // peers' pushes arrive on their own connections)
        long prev = s->pushed_rounds[{key, sender}];
        bool skew_ok = s->cv.wait_until(
            lk,
            std::chrono::steady_clock::now() +
                std::chrono::seconds(600),
            [&] { return s->completed_rounds[key] >= prev; });
        if (!skew_ok) {
          lk.unlock();
          send_err(fd, "sync push round skew on key " + key +
                           ": merge never completed");
          continue;
        }
        s->pushed_rounds[{key, sender}] = prev + 1;
        auto& acc = s->pending[key];
        if (acc.empty()) acc.assign(n, 0.0);
        for (uint64_t i = 0; i < n; ++i) acc[i] += grad[i];
        int cnt = ++s->pending_count[key];
        if (cnt == s->size) {
          std::vector<double> merged = std::move(acc);
          s->pending.erase(key);
          s->pending_count[key] = 0;
          s->completed_rounds[key] += 1;
          urc = apply_update(s, key, merged, /*is_async=*/false);
        }
      }
      s->cv.notify_all();
      lk.unlock();
      if (urc != 0)
        send_err(fd, "optimizer rule raised for key " + key);
      else
        send_resp(fd, 0, {});
    } else if (op == 5) {  // SPUSH (row-sparse, O(nnz) wire)
      int32_t sender;
      uint8_t mode, dt;
      uint64_t nrows, rowlen;
      std::memcpy(&sender, p, 4);
      p += 4;
      mode = static_cast<uint8_t>(*p++);
      dt = static_cast<uint8_t>(*p++);
      std::memcpy(&nrows, p, 8);
      p += 8;
      std::memcpy(&rowlen, p, 8);
      p += 8;
      uint64_t avail = static_cast<uint64_t>(end - p);
      if (dt > 7 || nrows > (1u << 28) || rowlen > (1u << 28) ||
          nrows * 8 > avail ||
          nrows * rowlen > (avail - nrows * 8) / esize(dt)) {
        send_err(fd, "short spush payload");
        continue;
      }
      const int64_t* rows = reinterpret_cast<const int64_t*>(p);
      const char* vals = p + nrows * 8;
      std::unique_lock<std::mutex> lk(s->mu);
      auto it = s->values.find(key);
      if (it == s->values.end()) {
        lk.unlock();
        send_err(fd, "spush to uninitialized key " + key);
        continue;
      }
      TVal& tv = it->second;
      uint64_t total = tv.n;
      bool oob = false;
      for (uint64_t r = 0; r < nrows; ++r) {
        // division form: (rows[r]+1)*rowlen can wrap for huge indices
        if (rows[r] < 0 ||
            (rowlen != 0 &&
             static_cast<uint64_t>(rows[r]) + 1 > total / rowlen))
          oob = true;
      }
      if (oob) {
        lk.unlock();
        send_err(fd, "spush row out of range for key " + key);
        continue;
      }
      if (s->profiling) {
        s->n_spush++;
        s->bytes_in += nrows * 8 + nrows * rowlen * esize(dt);
      }
      auto scatter_add_value = [&]() {
        for (uint64_t r = 0; r < nrows; ++r) {
          uint64_t base = rows[r] * rowlen;
          for (uint64_t j = 0; j < rowlen; ++j) {
            double g = get_el(vals, dt, r * rowlen + j);
            set_el(tv.raw.data(), tv.dt, base + j,
                   get_el(tv.raw.data(), tv.dt, base + j) + g);
          }
        }
      };
      if (mode == 1) {  // async: apply immediately
        scatter_add_value();
      } else {          // sync: merge all W per round
        long prev = s->pushed_rounds[{key, sender}];
        bool skew_ok = s->cv.wait_until(
            lk,
            std::chrono::steady_clock::now() +
                std::chrono::seconds(600),
            [&] { return s->completed_rounds[key] >= prev; });
        if (!skew_ok) {
          lk.unlock();
          send_err(fd, "sync spush round skew on key " + key);
          continue;
        }
        s->pushed_rounds[{key, sender}] = prev + 1;
        auto& acc = s->pending[key];
        if (acc.empty()) acc.assign(total, 0.0);
        for (uint64_t r = 0; r < nrows; ++r) {
          uint64_t base = rows[r] * rowlen;
          for (uint64_t j = 0; j < rowlen; ++j)
            acc[base + j] += get_el(vals, dt, r * rowlen + j);
        }
        int cnt = ++s->pending_count[key];
        if (cnt == s->size) {
          std::vector<double> merged = std::move(acc);
          s->pending.erase(key);
          s->pending_count[key] = 0;
          s->completed_rounds[key] += 1;
          int urc = apply_update(s, key, merged, /*is_async=*/false);
          if (urc != 0) {
            s->cv.notify_all();
            lk.unlock();
            send_err(fd, "optimizer rule raised for key " + key);
            continue;
          }
        }
      }
      s->cv.notify_all();
      lk.unlock();
      send_resp(fd, 0, {});
    } else if (op == 6) {  // SPULL (row subset, O(len(rows)) response)
      int32_t sender;
      uint64_t nrows, rowlen;
      std::memcpy(&sender, p, 4);
      p += 4;
      std::memcpy(&nrows, p, 8);
      p += 8;
      std::memcpy(&rowlen, p, 8);
      p += 8;
      // same caps as SPUSH: a version-skewed frame with a huge rowlen
      // would wrap (rows[r]+1)*rowlen in uint64 below and read out of
      // bounds
      if (nrows > (1u << 28) || rowlen > (1u << 28) ||
          nrows > static_cast<uint64_t>(end - p) / 8) {
        send_err(fd, "short spull payload");
        continue;
      }
      const int64_t* rows = reinterpret_cast<const int64_t*>(p);
      std::unique_lock<std::mutex> lk(s->mu);
      bool ok = s->cv.wait_until(
          lk,
          std::chrono::steady_clock::now() + std::chrono::seconds(600),
          [&] {
            if (s->values.find(key) == s->values.end()) return false;
            auto pit = s->pushed_rounds.find({key, sender});
            long need =
                pit == s->pushed_rounds.end() ? 0 : pit->second;
            return s->completed_rounds[key] >= need;
          });
      if (!ok) {
        lk.unlock();
        send_err(fd, "spull timeout on key " + key);
        continue;
      }
      const TVal& v = s->values[key];
      uint64_t total = v.n;
      size_t es = esize(v.dt);
      if (s->profiling) {
        s->n_spull++;
        s->bytes_in += nrows * 8;
        s->bytes_out += nrows * rowlen * es;
      }
      std::vector<char> body;
      body.reserve(9 + nrows * rowlen * es);
      body.push_back(static_cast<char>(v.dt));
      put_u64(&body, nrows * rowlen);
      bool oob = false;
      for (uint64_t r = 0; r < nrows; ++r) {
        // division form: (rows[r]+1)*rowlen can wrap for huge indices
        if (rows[r] < 0 ||
            (rowlen != 0 &&
             static_cast<uint64_t>(rows[r]) + 1 > total / rowlen)) {
          oob = true;
          break;
        }
        const char* base = v.raw.data() + rows[r] * rowlen * es;
        body.insert(body.end(), base, base + rowlen * es);
      }
      lk.unlock();
      if (oob)
        send_err(fd, "spull row out of range for key " + key);
      else
        send_resp(fd, 2, body);
    } else if (op == 7) {  // CMD (SendCommandToServers)
      int32_t head;
      uint32_t blen;
      std::memcpy(&head, p, 4);
      p += 4;
      std::memcpy(&blen, p, 4);
      p += 4;
      if (blen > static_cast<uint64_t>(end - p)) {
        send_err(fd, "short cmd payload");
        continue;
      }
      std::string body(p, p + blen);
      bool ok = true;
      if (head == 0 && body.rfind("profile:", 0) == 0) {
        std::string sub = body.substr(8);
        std::lock_guard<std::mutex> lk(s->mu);
        if (sub == "start") {
          s->profiling = true;
          s->n_push = s->n_pull = s->n_spush = s->n_spull = 0;
          s->bytes_in = s->bytes_out = 0;
        } else if (sub == "stop") {
          s->profiling = false;
        } else if (sub.rfind("dump:", 0) == 0) {
          // per-shard file: every shard receives the broadcast
          std::string path =
              sub.substr(5) + ".r" + std::to_string(s->rank);
          FILE* f = std::fopen(path.c_str(), "w");
          if (f == nullptr) {
            ok = false;
          } else {
            std::fprintf(
                f,
                "{\"rank\": %d, \"profiling\": %s, \"push\": %llu, "
                "\"pull\": %llu, \"spush\": %llu, \"spull\": %llu, "
                "\"bytes_in\": %llu, \"bytes_out\": %llu}\n",
                s->rank, s->profiling ? "true" : "false",
                (unsigned long long)s->n_push,
                (unsigned long long)s->n_pull,
                (unsigned long long)s->n_spush,
                (unsigned long long)s->n_spull,
                (unsigned long long)s->bytes_in,
                (unsigned long long)s->bytes_out);
            std::fclose(f);
          }
        }
      }
      if (ok)
        send_resp(fd, 0, {});
      else
        send_err(fd, "cmd failed: " + body);
    } else if (op == 2) {  // PULL
      int32_t sender;
      std::memcpy(&sender, p, 4);
      std::unique_lock<std::mutex> lk(s->mu);
      bool ok = s->cv.wait_until(
          lk,
          std::chrono::steady_clock::now() + std::chrono::seconds(600),
          [&] {
            if (s->values.find(key) == s->values.end()) return false;
            auto pit = s->pushed_rounds.find({key, sender});
            long need =
                pit == s->pushed_rounds.end() ? 0 : pit->second;
            return s->completed_rounds[key] >= need;
          });
      if (!ok) {
        lk.unlock();
        send_err(fd, "pull timeout on key " + key);
        continue;
      }
      const TVal& v = s->values[key];
      if (s->profiling) {
        s->n_pull++;
        s->bytes_out += v.n * esize(v.dt);
      }
      uint8_t dt = v.dt;
      std::vector<char> raw = v.raw;  // copy under lock
      uint64_t n = v.n;
      lk.unlock();
      send_val(fd, dt, raw.data(), n);
    } else if (op == 3) {  // HB
      int32_t sender;
      std::memcpy(&sender, p, 4);
      {
        std::lock_guard<std::mutex> lk(s->mu);
        s->last_hb[sender] = now_sec();
      }
      send_resp(fd, 0, {});
    } else if (op == 4) {  // DEAD
      double timeout;
      std::memcpy(&timeout, p, 8);
      std::vector<int32_t> dead;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        double t = now_sec();
        for (int r = 0; r < s->size; ++r) {
          auto it = s->last_hb.find(r);
          if (it == s->last_hb.end() || t - it->second > timeout)
            dead.push_back(r);
        }
      }
      std::vector<char> body;
      uint32_t m = static_cast<uint32_t>(dead.size());
      body.insert(body.end(), reinterpret_cast<char*>(&m),
                  reinterpret_cast<char*>(&m) + 4);
      body.insert(body.end(),
                  reinterpret_cast<const char*>(dead.data()),
                  reinterpret_cast<const char*>(dead.data()) +
                      dead.size() * 4);
      send_resp(fd, 3, body);
    } else {
      send_err(fd, "unknown op");
    }
  }
}

void serve_conn(Shard* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  try {
    serve_conn_inner(s, fd);
  } catch (const std::exception& e) {
    // a bad frame must cost one connection, not the whole training
    // process (detached-thread exceptions call std::terminate)
    send_err(fd, std::string("ps native server exception: ") +
                     e.what());
  } catch (...) {
  }
  ::close(fd);
}

void accept_loop(Shard* s) {
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      {
        std::lock_guard<std::mutex> lk(s->mu);
        if (s->stopping) return;
      }
      // back off instead of busy-spinning on persistent failure
      // (EMFILE under fd exhaustion)
      ::usleep(10000);
      continue;
    }
    std::thread(serve_conn, s, fd).detach();
  }
}

}  // namespace

extern "C" {

// start the shard server; returns the listening port (or -1)
int ps_native_start(int rank, int size) {
  if (g_shard != nullptr) return g_shard->port;
  Shard* s = new Shard();
  s->rank = rank;
  s->size = size;
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) return -1;
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
               sizeof(one));
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = 0;
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0)
    return -1;
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                &alen);
  s->port = ntohs(addr.sin_port);
  if (::listen(s->listen_fd, 64) != 0) return -1;
  s->threads.emplace_back(accept_loop, s);
  s->threads.back().detach();
  g_shard = s;
  return s->port;
}

void ps_native_set_updater(updater_fn fn) {
  if (g_shard == nullptr) return;
  std::lock_guard<std::mutex> lk(g_shard->mu);
  g_shard->updater = fn;
}

}  // extern "C"
