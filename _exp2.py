"""Perf isolation: pure-JAX ResNet-50 train step, NHWC vs NCHW, vs framework.

Scratch experiment — not part of the package (deleted before commit).
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax


def conv(x, w, stride, layout):
    if layout == "NHWC":
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    else:
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    k = w.shape[0] if layout == "NHWC" else w.shape[2]
    pad = (k - 1) // 2
    return lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=dn)


def bn(x, p, layout):
    axis = 3 if layout == "NHWC" else 1
    red = tuple(i for i in range(4) if i != axis)
    x32 = x.astype(jnp.float32)
    mean = x32.mean(red)
    var = x32.var(red)
    shape = [1] * 4
    shape[axis] = x.shape[axis]
    out = (x32 - mean.reshape(shape)) * (
        lax.rsqrt(var + 1e-5) * p["gamma"].reshape(shape)
    ) + p["beta"].reshape(shape)
    return out.astype(x.dtype)


def make_params(key, layout):
    """ResNet-50 v1 params."""
    params = {}
    init = jax.nn.initializers.he_normal()

    def cw(key, cin, cout, k):
        if layout == "NHWC":
            return init(key, (k, k, cin, cout), jnp.float32)
        return init(key, (cout, cin, k, k), jnp.float32)

    keys = iter(jax.random.split(key, 200))
    params["c0"] = cw(next(keys), 3, 64, 7)
    params["bn0"] = {"gamma": jnp.ones(64), "beta": jnp.zeros(64)}
    blocks = [(3, 64, 256), (4, 128, 512), (6, 256, 1024), (3, 512, 2048)]
    cin = 64
    for si, (n, mid, out) in enumerate(blocks):
        for bi in range(n):
            pre = f"s{si}b{bi}"
            params[pre + "c1"] = cw(next(keys), cin, mid, 1)
            params[pre + "bn1"] = {"gamma": jnp.ones(mid), "beta": jnp.zeros(mid)}
            params[pre + "c2"] = cw(next(keys), mid, mid, 3)
            params[pre + "bn2"] = {"gamma": jnp.ones(mid), "beta": jnp.zeros(mid)}
            params[pre + "c3"] = cw(next(keys), mid, out, 1)
            params[pre + "bn3"] = {"gamma": jnp.ones(out), "beta": jnp.zeros(out)}
            if bi == 0:
                params[pre + "cd"] = cw(next(keys), cin, out, 1)
                params[pre + "bnd"] = {"gamma": jnp.ones(out), "beta": jnp.zeros(out)}
            cin = out
    params["fc_w"] = jax.random.normal(next(keys), (2048, 1000)) * 0.01
    params["fc_b"] = jnp.zeros(1000)
    return params


def forward(params, x, layout):
    cast = lambda w: w.astype(jnp.bfloat16)  # noqa: E731
    h = conv(x, cast(params["c0"]), 2, layout)
    h = bn(h, params["bn0"], layout)
    h = jax.nn.relu(h)
    dims = (1, 2) if layout == "NHWC" else (2, 3)
    h = lax.reduce_window(
        h, -jnp.inf, lax.max,
        (1, 3, 3, 1) if layout == "NHWC" else (1, 1, 3, 3),
        (1, 2, 2, 1) if layout == "NHWC" else (1, 1, 2, 2),
        [(0, 0), (1, 1), (1, 1), (0, 0)] if layout == "NHWC"
        else [(0, 0), (0, 0), (1, 1), (1, 1)])
    blocks = [(3, 64, 256), (4, 128, 512), (6, 256, 1024), (3, 512, 2048)]
    for si, (n, mid, out) in enumerate(blocks):
        for bi in range(n):
            pre = f"s{si}b{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            idn = h
            h2 = conv(h, cast(params[pre + "c1"]), 1, layout)
            h2 = jax.nn.relu(bn(h2, params[pre + "bn1"], layout))
            h2 = conv(h2, cast(params[pre + "c2"]), stride, layout)
            h2 = jax.nn.relu(bn(h2, params[pre + "bn2"], layout))
            h2 = conv(h2, cast(params[pre + "c3"]), 1, layout)
            h2 = bn(h2, params[pre + "bn3"], layout)
            if bi == 0:
                idn = conv(idn, cast(params[pre + "cd"]), stride, layout)
                idn = bn(idn, params[pre + "bnd"], layout)
            h = jax.nn.relu(h2 + idn)
    h = h.mean(dims).astype(jnp.float32)
    return h @ params["fc_w"] + params["fc_b"]


def main():
    layout = sys.argv[1] if len(sys.argv) > 1 else "NHWC"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    mode = sys.argv[3] if len(sys.argv) > 3 else "train"
    key = jax.random.PRNGKey(0)
    params = make_params(key, layout)
    if layout == "NHWC":
        x = jnp.asarray(onp.random.rand(batch, 224, 224, 3),
                        dtype=jnp.bfloat16)
    else:
        x = jnp.asarray(onp.random.rand(batch, 3, 224, 224),
                        dtype=jnp.bfloat16)
    y = jnp.asarray(onp.random.randint(0, 1000, size=(batch,)))

    def loss_fn(params, x, y):
        logits = forward(params, x, layout)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], 1).mean()

    if mode == "fwd":
        f = jax.jit(lambda p, x: forward(p, x, layout))
        out = jax.block_until_ready(f(params, x))
        n = 30
        t0 = time.perf_counter()
        for _ in range(n):
            out = f(params, x)
        _ = float(out.sum())
        dt = (time.perf_counter() - t0) / n
        print(f"pure-jax {layout} bs{batch} fwd: {dt*1e3:.2f} ms "
              f"({batch/dt:.0f} img/s)")
        return

    mom = jax.tree_util.tree_map(jnp.zeros_like, params)

    @jax.jit
    def step(params, mom, x, y):
        loss, g = jax.value_and_grad(loss_fn)(params, x, y)
        mom = jax.tree_util.tree_map(lambda m, g: 0.9 * m + g, mom, g)
        params = jax.tree_util.tree_map(
            lambda p, m: p - 0.1 * m, params, mom)
        return loss, params, mom

    loss, params, mom = step(params, mom, x, y)
    _ = float(loss)
    n = 30
    t0 = time.perf_counter()
    for _ in range(n):
        loss, params, mom = step(params, mom, x, y)
    _ = float(loss)
    dt = (time.perf_counter() - t0) / n
    print(f"pure-jax {layout} bs{batch} train: {dt*1e3:.2f} ms/step "
          f"({batch/dt:.0f} img/s)")


if __name__ == "__main__":
    main()
