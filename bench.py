"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Reference baseline (BASELINE.md): 363.69 img/s — MXNet 1.2 ResNet-50
training, batch 128, single V100 (docs perf.md:243-254).  The driver runs
this on the real TPU chip and records the JSON line.

One fused XLA program per step (fwd+bwd+SGD momentum, donated buffers),
bf16 activations/weights with fp32 BatchNorm statistics — the MXU-native
configuration.
"""
from __future__ import annotations

import json
import time

import numpy as onp


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_train_step

    batch = 128
    net = gluon.model_zoo.vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Xavier())
    net(mx.nd.zeros((1, 3, 224, 224)))  # resolve deferred shapes
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step_fn, params, opt_state = make_train_step(
        net, loss_fn, optimizer="sgd", learning_rate=0.1, momentum=0.9,
        donate=False, compute_dtype="bfloat16")

    import jax
    import jax.numpy as jnp

    x = jnp.asarray(onp.random.rand(batch, 3, 224, 224), dtype=jnp.bfloat16
                    ).astype(jnp.float32)
    y = jnp.asarray(
        onp.random.randint(0, 1000, size=(batch,)).astype("float32"))
    key = jax.random.key(0)

    # warmup / compile
    loss, params, opt_state = step_fn(params, opt_state, x, y, key, 1.0)
    jax.block_until_ready(loss)

    n_steps = 20
    t0 = time.perf_counter()
    for i in range(n_steps):
        loss, params, opt_state = step_fn(
            params, opt_state, x, y, key, float(i + 2))
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    throughput = batch * n_steps / dt

    baseline = 363.69  # V100 bs128 (BASELINE.md row 1)
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(throughput, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(throughput / baseline, 3),
    }))


if __name__ == "__main__":
    main()
