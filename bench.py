"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Reference baseline (BASELINE.md): 363.69 img/s — MXNet 1.2 ResNet-50
training, batch 128, single V100 (docs perf.md:243-254).  The driver runs
this on the real TPU chip and records the JSON line.

One fused XLA program per step (fwd+bwd+SGD momentum, donated buffers),
bf16 activations/weights with fp32 BatchNorm statistics — the MXU-native
configuration.

Perf note (round 2): the model is initialized ON the accelerator
(ctx=mx.gpu(0)) and the whole bench path never executes a single op on
the JAX CPU backend.  Mixing host-backend eager compute into a TPU
process forces per-dispatch synchronization with the device runtime and
serializes the step stream (measured: 57 ms/step vs 1.9 ms/step for the
identical executable).  Keep eager work on-device or in numpy.
"""
from __future__ import annotations

import json
import time

import numpy as onp


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_train_step

    import jax
    import jax.numpy as jnp

    batch = 128
    ctx = mx.gpu(0)  # falls back to cpu on accelerator-less hosts
    net = gluon.model_zoo.vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Xavier(), ctx=ctx)
    net(mx.nd.zeros((1, 3, 224, 224), ctx=ctx))  # resolve deferred shapes
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step_fn, params, opt_state = make_train_step(
        net, loss_fn, optimizer="sgd", learning_rate=0.1, momentum=0.9,
        donate=False, compute_dtype="bfloat16")

    x = jnp.asarray(onp.random.rand(batch, 3, 224, 224), dtype=jnp.bfloat16)
    y = jnp.asarray(
        onp.random.randint(0, 1000, size=(batch,)).astype("float32"))
    key = jax.random.key(0)

    # warmup / compile
    loss, params, opt_state = step_fn(params, opt_state, x, y, key, 1.0)
    jax.block_until_ready(loss)

    n_steps = 50
    t0 = time.perf_counter()
    for i in range(n_steps):
        loss, params, opt_state = step_fn(
            params, opt_state, x, y, key, float(i + 2))
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    throughput = batch * n_steps / dt

    baseline = 363.69  # V100 bs128 (BASELINE.md row 1)
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(throughput, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(throughput / baseline, 3),
    }))


if __name__ == "__main__":
    main()
