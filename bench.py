"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Reference baseline (BASELINE.md): 363.69 img/s — MXNet 1.2 ResNet-50
training, batch 128, single V100 (docs perf.md:243-254).  The driver runs
this on the real TPU chip and records the JSON line.

One fused XLA program per step (fwd+bwd+SGD momentum, bf16 activations/
weights, fp32 BatchNorm statistics with a custom-VJP fused backward —
the cuDNN BatchNormBackward analog).  The model is built with
``no_bias=True`` — the reference's own benchmark symbol
(example/image-classification/symbols/resnet.py) sets no_bias=True on
every conv; the gluon-zoo 1x1 biases it omits are mathematically inert
under the following BatchNorm (zero gradient).

MEASUREMENT NOTE (round 3/4): on the `axon` TPU tunnel,
``jax.block_until_ready`` returns WITHOUT draining execution, and the
dispatch+readback constant jitters by tens of ms between calls —
host-side timing loops are untrustworthy at both ends (round-2's
66,520 img/s was an enqueue-rate artifact; round-3's K-sweep still
carried ~10% readback jitter).  Round 4 times a ``lax.fori_loop`` of
K REAL train steps (params/opt-state threaded through the carry, so
iterations serialize by construction) as ONE device program with ONE
final loss readback; the marginal per-step cost comes from two K
values, which cancels the constant exactly once.  Verified against the
device trace (jit_step wall time) to <1%.

Also reported: achieved TFLOP/s from ``compiled.cost_analysis()`` and
MFU relative to the chip's bf16 matmul peak measured in-process by a
4096^3 chained probe (same methodology; measures 195 TF/s on v5e,
consistent with the 197 TF/s spec sheet).
"""
from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _matmul_peak_tflops():
    """Measured bf16 matmul roofline of this chip via the device-chained
    timer (benchmark/devtime.py)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmark"))
    import jax.numpy as jnp
    from devtime import device_chain_time

    m = 4096
    a = jnp.asarray(onp.random.rand(m, m), jnp.bfloat16)
    dt, _ = device_chain_time(lambda p, q: p @ q, [a, a],
                              target_spread=0.4)
    return 2 * m**3 / dt / 1e12


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_train_step

    import jax
    import jax.numpy as jnp

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    layout = "NCHW"  # NHWC supported too; identical on this chip (XLA
    #                  assigns physical layouts itself — measured r03/r04)
    ctx = mx.gpu(0)  # falls back to cpu on accelerator-less hosts
    net = gluon.model_zoo.vision.resnet50_v1(
        classes=1000, layout=layout, no_bias=True)
    net.initialize(init=mx.init.Xavier(), ctx=ctx)
    shp = (1, 3, 224, 224) if layout == "NCHW" else (1, 224, 224, 3)
    net(mx.nd.zeros(shp, ctx=ctx))  # resolve deferred shapes
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step_fn, params, opt_state = make_train_step(
        net, loss_fn, optimizer="sgd", learning_rate=0.1, momentum=0.9,
        donate=False, compute_dtype="bfloat16")

    xshp = (batch, 3, 224, 224) if layout == "NCHW" else (batch, 224, 224, 3)
    x = jnp.asarray(onp.random.rand(*xshp), dtype=jnp.bfloat16)
    y = jnp.asarray(
        onp.random.randint(0, 1000, size=(batch,)).astype("float32"))
    key = jax.random.key(0)

    # static program cost (flops/bytes) for the MFU report
    compiled = step_fn.lower(params, opt_state, x, y, key, 1.0).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    step_flops = float(ca.get("flops", 0.0))
    step_bytes = float(ca.get("bytes accessed", 0.0))

    @partial(jax.jit, static_argnums=(0,))
    def multi_step(k, p, o):
        def body(i, carry):
            p_, o_, _ = carry
            loss, p2, o2 = step_fn(p_, o_, x, y, key,
                                   (i + 1).astype(jnp.float32))
            return (p2, o2, loss)

        return jax.lax.fori_loop(
            0, k, body, (p, o, jnp.float32(0.0)))[2]

    def run(k):
        t0 = time.perf_counter()
        loss = multi_step(k, params, opt_state)
        _ = float(loss)  # materialize: drains the device pipeline
        return time.perf_counter() - t0

    K1, K2 = 3, 33  # 30-step spread (~1.4 s) dwarfs the ~40 ms jitter
    run(K1)
    run(K2)  # compile both loop programs before the clock
    trials = []
    for _ in range(3):
        t1, t2 = run(K1), run(K2)
        trials.append((t2 - t1) / (K2 - K1))
    dt = _median(trials)
    throughput = batch / dt

    peak = _matmul_peak_tflops()
    achieved = step_flops / dt / 1e12
    baseline = 363.69  # V100 bs128 (BASELINE.md row 1)
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(throughput, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(throughput / baseline, 3),
        "ms_per_step": round(dt * 1e3, 2),
        "achieved_tflops": round(achieved, 1),
        "matmul_peak_tflops": round(peak, 1),
        "mfu": round(achieved / peak, 3),
        "step_gflops": round(step_flops / 1e9, 1),
        "step_gbytes": round(step_bytes / 1e9, 1),
        "methodology": "fori_loop-chained K-step programs, two-K slope, "
                       "single loss readback (host timing loops are "
                       "unreliable on the axon tunnel: block_until_ready "
                       "does not drain and dispatch jitters ~40 ms)",
    }))


if __name__ == "__main__":
    main()
